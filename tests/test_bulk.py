"""Bulk tier tests (ISSUE 18): BulkPolicy validation, the per-bucket
BulkQueue, FoldRequest.qos + the X-Qos wire header, and the scheduler
choreography — bulk founds batches only when online is idle, steals
freed rows under continuous admission, a full queue rejects, a burn
gate (stub SLO engine) blocks founding but not a draining stop, an
undrained stop cancels, and the headline move: in-flight bulk rows
checkpoint-and-yield when online burn crosses BulkPolicy.max_burn,
then resume from the spilled checkpoint byte-equal once burn recedes.

The scripted stub carries a PYTREE state (spillable carry) whose
coords accumulate multiplicatively per step, so a resumed loop is
distinguishable from a refold by its step count while staying
byte-comparable to an uninterrupted reference run.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.fleet.rpc import (decode_request, encode_request,
                                      request_headers)
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, BulkPolicy, BulkQueue,
                                  FoldRequest, QueueFullError,
                                  RecyclePolicy, RetryPolicy, Scheduler,
                                  SchedulerConfig, ServeMetrics)


# -- pytree-carry step stub (own class: pytree registration is global
# per type, so this file registers its own, never test_checkpoints') --


class _BkState:
    def __init__(self, coords, confidence, ids, counts):
        self.coords = coords
        self.confidence = confidence
        self.ids = ids
        self.counts = counts


jax.tree_util.register_pytree_node(
    _BkState,
    lambda s: ((s.coords, s.confidence, s.ids, s.counts), None),
    lambda aux, ch: _BkState(*ch))


class _BkStub:
    """Deterministic pytree-carry executor with a one-shot gate: the
    step at `gate_at` blocks until `release` so the test can flip the
    burn signal (or submit racing work) while a loop is provably
    mid-flight."""

    def __init__(self):
        self.calls = []
        self.reached = threading.Event()
        self.release = threading.Event()
        self.gate_at = None
        self._lock = threading.Lock()

    def run_init(self, batch, trace=None, devices=None, mesh_shape=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        with self._lock:
            self.calls.append(("init", [int(i) for i in seq[:, 0]]))
        return _BkState(jnp.zeros((b, n, 3), jnp.float32),
                        jnp.zeros((b, n), jnp.float32),
                        jnp.asarray(seq[:, 0], jnp.int32),
                        jnp.zeros((b,), jnp.int32))

    def run_init_rows(self, batch, state, row_mask, trace=None,
                      devices=None, mesh_shape=None, span_attrs=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        mask = jnp.asarray(np.asarray(row_mask))
        with self._lock:
            self.calls.append(
                ("init_rows",
                 [int(i) for i in seq[:, 0][np.asarray(row_mask)]]))
        return _BkState(
            jnp.where(mask[:, None, None],
                      jnp.zeros((b, n, 3), jnp.float32), state.coords),
            jnp.where(mask[:, None],
                      jnp.zeros((b, n), jnp.float32), state.confidence),
            jnp.where(mask, jnp.asarray(seq[:, 0], jnp.int32), state.ids),
            jnp.where(mask, 0, state.counts))

    def run_step(self, batch, state, recycle_index, trace=None,
                 devices=None, mesh_shape=None, span_attrs=None):
        with self._lock:
            self.calls.append(("step", int(recycle_index)))
            gated = self.gate_at is not None \
                and recycle_index == self.gate_at
            if gated:
                self.gate_at = None
        if gated:
            self.reached.set()
            assert self.release.wait(timeout=60)
        return _BkState(
            state.coords * jnp.float32(1.01) + jnp.float32(1.0)
            + state.ids[:, None, None].astype(jnp.float32) * 0.001,
            state.confidence, state.ids, state.counts + 1)

    def stats(self):
        return {"calls": len(self.calls)}

    def steps(self):
        with self._lock:
            return sum(1 for c in self.calls if c[0] == "step")

    def kinds(self):
        with self._lock:
            return [c[0] for c in self.calls]


class _Slo:
    """SLO engine stand-in with a dial: report() mirrors the real
    engine's classes->latency->burn_rate shape."""

    def __init__(self, burn=0.0):
        self.burn = burn

    def report(self):
        return {"classes": {"online": {"latency":
                                       {"burn_rate": self.burn}}}}


def _sched(stub, num_recycles=6, spill_dir=None, bulk=None, slo=None,
           continuous=False, max_batch=2, registry=None, **kw):
    registry = registry or MetricsRegistry()
    retry_kw = dict(backoff_base_s=0.0, jitter=0.0)
    if spill_dir is not None:
        retry_kw.update(checkpoint_every=1, checkpoint_spill=spill_dir)
    return Scheduler(
        stub, BucketPolicy((32,)),
        SchedulerConfig(max_batch_size=max_batch, max_wait_ms=5.0,
                        num_recycles=num_recycles, msa_depth=0,
                        poll_ms=2.0),
        recycle_policy=RecyclePolicy(converge_tol=0.0,
                                     continuous=continuous),
        retry=RetryPolicy(**retry_kw),
        metrics=ServeMetrics(registry=registry), registry=registry,
        bulk=bulk, slo=slo, **kw)


def _req(token=7, length=12, qos="online", deadline_s=None):
    return FoldRequest(seq=np.full(length, token, np.int32), qos=qos,
                       deadline_s=deadline_s)


def _wait_for(cond, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


# -- policy + queue units ---------------------------------------------


class TestBulkPolicy:
    def test_defaults_valid(self):
        p = BulkPolicy()
        assert p.max_burn == 1.0 and p.max_pending == 10000 \
            and p.check_interval_s == 1.0

    def test_validation(self):
        with pytest.raises(ValueError):
            BulkPolicy(max_burn=0.0)
        with pytest.raises(ValueError):
            BulkPolicy(max_pending=0)
        with pytest.raises(ValueError):
            BulkPolicy(check_interval_s=-1.0)


class _Item:
    def __init__(self, name, enqueued_at):
        self.name = name
        self.enqueued_at = enqueued_at


class TestBulkQueue:
    def test_fifo_per_bucket_and_push_front(self):
        q = BulkQueue()
        q.push(32, "a")
        q.push(32, "b")
        q.push(64, "c")
        assert len(q) == 3
        assert q.pending_for(32) == 2
        q.push_front(32, "z")      # a yielded loop jumps the campaign
        assert [q.take(32) for _ in range(3)] == ["z", "a", "b"]
        assert q.take(32) is None
        assert q.take(64) == "c"
        assert len(q) == 0

    def test_buckets_oldest_head_first(self):
        q = BulkQueue()
        q.push(64, _Item("late", 20.0))
        q.push(32, _Item("early", 10.0))
        q.push(64, _Item("later", 30.0))   # behind "late" in its bucket
        assert q.buckets() == [32, 64]

    def test_drain_and_snapshot(self):
        q = BulkQueue()
        q.push(32, "a")
        q.push(64, "b")
        assert q.snapshot() == {"pending": 2,
                                "buckets": {32: 1, 64: 1}}
        out = q.drain()
        assert sorted(out) == ["a", "b"]
        assert len(q) == 0 and q.snapshot()["pending"] == 0


# -- qos field + wire header ------------------------------------------


class TestQosWire:
    def test_request_qos_validation(self):
        assert _req().qos == "online"
        assert _req(qos="bulk").qos == "bulk"
        with pytest.raises(ValueError):
            _req(qos="batchy")

    def test_online_request_has_no_qos_header(self):
        h = request_headers(_req())
        assert "X-Qos" not in h

    def test_bulk_qos_roundtrips_over_the_wire(self):
        req = _req(token=5, qos="bulk")
        h = request_headers(req)
        assert h["X-Qos"] == "bulk"
        got = decode_request(encode_request(req), h)
        assert got.qos == "bulk"
        assert np.array_equal(got.seq, req.seq)

    def test_absent_header_decodes_online(self):
        req = _req(token=5)
        got = decode_request(encode_request(req),
                             request_headers(req))
        assert got.qos == "online"


# -- scheduler choreography -------------------------------------------


class TestSchedulerBulk:
    def test_bulk_founds_when_idle(self):
        """An idle scheduler folds bulk work and counts the admit."""
        stub = _BkStub()
        with _sched(stub, num_recycles=2, bulk=BulkPolicy()) as sched:
            resp = sched.submit(_req(qos="bulk")).result(timeout=60)
            assert resp.ok and resp.source == "fold"
            stats = sched.serve_stats()["bulk"]
            assert stats["admits"] == 1 and stats["pending"] == 0
            assert stats["yields"] == 0 and not stats["gated"]

    def test_online_founds_first(self):
        """A racing online + bulk pair: online founds the first batch;
        bulk (never a founder while online work is pending) follows."""
        stub = _BkStub()
        stub.gate_at = 1           # step indexes are 1-based
        with _sched(stub, num_recycles=2, max_batch=1,
                    bulk=BulkPolicy()) as sched:
            t_on = sched.submit(_req(token=3))
            assert stub.reached.wait(timeout=30)
            t_bk = sched.submit(_req(token=9, qos="bulk"))
            stub.release.set()
            assert t_on.result(timeout=60).ok
            assert t_bk.result(timeout=60).ok
            inits = [c for c in stub.calls if c[0] == "init"]
            assert inits[0][1] == [3] and [9] in [c[1] for c in inits]

    def test_bulk_steals_freed_row_under_continuous_admission(self):
        """With continuous admission on, queued bulk work rides a
        freed row of a RUNNING online batch (init_rows, not a founded
        batch) once the online queues are empty."""
        stub = _BkStub()
        stub.gate_at = 1
        with _sched(stub, num_recycles=6, continuous=True,
                    bulk=BulkPolicy()) as sched:
            t_on = sched.submit(_req(token=3))
            assert stub.reached.wait(timeout=30)
            t_bk = sched.submit(_req(token=9, qos="bulk"))
            stub.release.set()
            assert t_on.result(timeout=60).ok
            assert t_bk.result(timeout=60).ok
            assert ("init_rows", [9]) in stub.calls
            assert sched.serve_stats()["bulk"]["admits"] == 1

    def test_without_bulk_policy_qos_folds_online(self):
        """No BulkPolicy -> qos='bulk' is just an online fold: no bulk
        stats key, no bulk metric names minted."""
        stub = _BkStub()
        reg = MetricsRegistry()
        with _sched(stub, num_recycles=2, registry=reg) as sched:
            assert sched.submit(_req(qos="bulk")).result(timeout=60).ok
            assert "bulk" not in sched.serve_stats()
        names = set(reg.snapshot())
        assert not {"serve_bulk_admits_total", "serve_bulk_yields_total",
                    "serve_bulk_gated"} & names

    def test_bulk_metric_names_minted_with_policy(self):
        reg = MetricsRegistry()
        sched = _sched(_BkStub(), bulk=BulkPolicy(), registry=reg)
        assert {"serve_bulk_admits_total", "serve_bulk_yields_total",
                "serve_bulk_gated"} <= set(reg.snapshot())
        sched.stop(drain=False)

    def test_queue_full_rejects_and_drain_ignores_gate(self):
        """max_pending bounds the bulk queue (QueueFullError, counted
        as rejected); a draining stop resolves the gated backlog —
        terminal resolution beats throttling."""
        stub = _BkStub()
        slo = _Slo(burn=10.0)      # gate closed: nothing founds
        sched = _sched(stub, num_recycles=2, slo=slo,
                       bulk=BulkPolicy(max_pending=1,
                                       check_interval_s=0.0))
        sched.start()
        t1 = sched.submit(_req(token=3, qos="bulk"))
        with pytest.raises(QueueFullError):
            sched.submit(_req(token=9, qos="bulk"))
        stats = sched.serve_stats()["bulk"]
        assert stats["pending"] == 1 and stats["rejected"] == 1
        sched.stop(drain=True)
        assert t1.result(timeout=60).ok

    def test_stop_without_drain_cancels_pending_bulk(self):
        stub = _BkStub()
        sched = _sched(stub, slo=_Slo(burn=10.0),
                       bulk=BulkPolicy(check_interval_s=0.0))
        sched.start()
        t1 = sched.submit(_req(qos="bulk"))
        sched.stop(drain=False)
        assert t1.result(timeout=60).status == "cancelled"

    def test_expired_bulk_sheds_at_admission(self):
        """Bulk entries shed at take time, not via the online sweep."""
        stub = _BkStub()
        slo = _Slo(burn=10.0)
        sched = _sched(stub, slo=slo,
                       bulk=BulkPolicy(check_interval_s=0.0))
        sched.start()
        try:
            t1 = sched.submit(_req(qos="bulk", deadline_s=0.01))
            time.sleep(0.05)
            slo.burn = 0.0         # open the gate; admission finds it dead
            resp = t1.result(timeout=60)
            assert resp.status == "shed"
        finally:
            sched.stop(drain=False)


class TestYieldUnderBurn:
    def test_bulk_yields_then_resumes_byte_equal(self, tmp_path):
        """The acceptance choreography: a mid-flight bulk loop
        checkpoint-and-yields at the first admission gap after online
        burn crosses max_burn (admits gate, the row frees), then —
        burn receding — resumes from the spilled checkpoint and
        finishes byte-equal to an uninterrupted run with ZERO repeated
        recycles."""
        stub = _BkStub()
        stub.gate_at = 1
        slo = _Slo(burn=0.0)
        sched = _sched(stub, num_recycles=6,
                       spill_dir=str(tmp_path / "spill"), slo=slo,
                       bulk=BulkPolicy(max_burn=1.0,
                                       check_interval_s=0.0))
        sched.start()
        try:
            t1 = sched.submit(_req(token=9, qos="bulk"))
            assert stub.reached.wait(timeout=30)
            slo.burn = 10.0        # online burn spikes mid-step
            stub.release.set()
            _wait_for(
                lambda: sched.serve_stats()["bulk"]["yields"] >= 1,
                what="bulk yield")
            stats = sched.serve_stats()["bulk"]
            assert stats["gated"] and stats["pending"] == 1
            assert not t1.done()
            steps_at_yield = stub.steps()
            slo.burn = 0.0         # burn recedes: the campaign resumes
            resp = t1.result(timeout=60)
            assert resp.ok and resp.source == "fold"
        finally:
            sched.stop(drain=False)
        # no recycle ran twice: resumed exactly at the spilled age
        assert stub.steps() == 6
        assert steps_at_yield < 6
        spill = sched.serve_stats()["resilience"]["checkpoint_spill"]
        assert spill["spill_resumes"] >= 1
        assert sched.serve_stats()["bulk"]["yields"] == 1

        # byte-equality against an uninterrupted reference loop
        ref_stub = _BkStub()
        with _sched(ref_stub, num_recycles=6) as ref:
            ref_resp = ref.submit(_req(token=9)).result(timeout=60)
        assert ref_resp.ok
        assert np.array_equal(resp.coords, ref_resp.coords)
        assert np.array_equal(resp.confidence, ref_resp.confidence)

    def test_gate_reopens_without_yield_when_no_store(self, tmp_path):
        """Without a spill store a yield would refold from zero, so
        bulk rows run to completion even under burn."""
        stub = _BkStub()
        stub.gate_at = 1
        slo = _Slo(burn=0.0)
        sched = _sched(stub, num_recycles=4, slo=slo,
                       bulk=BulkPolicy(max_burn=1.0,
                                       check_interval_s=0.0))
        sched.start()
        try:
            t1 = sched.submit(_req(token=9, qos="bulk"))
            assert stub.reached.wait(timeout=30)
            slo.burn = 10.0
            stub.release.set()
            resp = t1.result(timeout=60)
            assert resp.ok
            assert sched.serve_stats()["bulk"]["yields"] == 0
        finally:
            sched.stop(drain=False)
        assert stub.steps() == 4
