"""Geometry-layer tests: shape contracts mirroring the reference's
tests/test_utils.py plus golden-value and property tests the reference lacks
(SURVEY.md §4: closed-form checks for Kabsch/RMSD/dihedrals, equivariance)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import constants
from alphafold2_tpu.core import geometry as geo
from alphafold2_tpu.core import quaternion as quat
from alphafold2_tpu.core.rigid import Rigid

pytestmark = pytest.mark.quick


def random_rotation(key):
    q = jax.random.normal(key, (4,))
    return quat.quaternion_to_matrix(q / jnp.linalg.norm(q))


class TestDistogram:
    def test_bucketed_distance_matrix(self):
        coords = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 3)) * 5
        mask = jnp.ones((2, 16), dtype=bool).at[:, -3:].set(False)
        buckets = geo.bucketed_distance_matrix(coords, mask)
        assert buckets.shape == (2, 16, 16)
        valid = buckets[:, :13, :13]
        assert (valid >= 0).all() and (valid < constants.DISTOGRAM_BUCKETS).all()
        assert (buckets[:, -3:, :] == constants.IGNORE_INDEX).all()

    def test_bucket_values(self):
        # distance 2.5 lands right of boundary 2.0 -> bucket 1 (36 bins of
        # 0.5A from 2A); below 2A -> bucket 0; above 20A -> last bucket
        coords = jnp.array([[[0.0, 0, 0], [2.25, 0, 0], [50.0, 0, 0]]])
        mask = jnp.ones((1, 3), dtype=bool)
        buckets = geo.bucketed_distance_matrix(coords, mask)
        assert buckets[0, 0, 1] == 1
        assert buckets[0, 0, 2] == constants.DISTOGRAM_BUCKETS - 1
        assert buckets[0, 0, 0] == 0

    def test_center_distogram(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (1, 12, 12, 37))
        probs = jax.nn.softmax(logits, -1)
        central, weights = geo.center_distogram(probs)
        assert central.shape == (1, 12, 12)
        assert weights.shape == (1, 12, 12)
        assert (jnp.diagonal(central, axis1=1, axis2=2) == 0).all()
        assert bool(jnp.isfinite(central).all() and jnp.isfinite(weights).all())

    def test_center_distogram_median(self):
        probs = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(2), (1, 8, 8, 37)), -1)
        central, _ = geo.center_distogram(probs, center="median")
        assert central.shape == (1, 8, 8)


class TestDihedral:
    def test_known_dihedral(self):
        # c1 sits at +y of the c2-c3 axis; c4 at +y -> cis (0), at -y ->
        # trans (pi), at +z -> +-pi/2
        c1 = jnp.array([1.0, 1.0, 0.0])
        c2 = jnp.array([1.0, 0.0, 0.0])
        c3 = jnp.array([0.0, 0.0, 0.0])
        c4_cis = jnp.array([-1.0, 1.0, 0.0])
        c4_trans = jnp.array([-1.0, -1.0, 0.0])
        assert np.isclose(geo.dihedral(c1, c2, c3, c4_cis), 0.0, atol=1e-5)
        assert np.isclose(abs(geo.dihedral(c1, c2, c3, c4_trans)), np.pi,
                          atol=1e-5)
        d90 = geo.dihedral(c1, c2, c3, jnp.array([0.0, 0.0, 1.0]))
        assert np.isclose(abs(d90), np.pi / 2, atol=1e-5)

    def test_rotation_invariance(self):
        key = jax.random.PRNGKey(3)
        pts = jax.random.normal(key, (4, 3))
        rot = random_rotation(jax.random.PRNGKey(4))
        d1 = geo.dihedral(*pts)
        d2 = geo.dihedral(*(pts @ rot))
        assert np.isclose(d1, d2, atol=1e-4)


class TestKabsch:
    def test_recovers_rotation(self):
        key = jax.random.PRNGKey(5)
        x = jax.random.normal(key, (1, 32, 3))
        rot = random_rotation(jax.random.PRNGKey(6))
        y = x @ rot + jnp.array([1.0, -2.0, 3.0])
        x_a, y_c = geo.kabsch(y, x)  # align y onto x
        assert float(geo.rmsd(x_a, y_c)[0]) < 1e-4

    def test_kabsch_rmsd_zero_for_rigid_transform(self):
        x = jax.random.normal(jax.random.PRNGKey(7), (2, 20, 3))
        rot = random_rotation(jax.random.PRNGKey(8))
        y = x @ rot + 5.0
        assert float(geo.kabsch_rmsd(x, y).max()) < 1e-4

    def test_masked(self):
        x = jax.random.normal(jax.random.PRNGKey(9), (1, 16, 3))
        rot = random_rotation(jax.random.PRNGKey(10))
        y = x @ rot
        # corrupt masked-out tail; alignment should ignore it
        y = y.at[:, -4:].add(100.0)
        mask = jnp.ones((1, 16), dtype=bool).at[:, -4:].set(False)
        assert float(geo.kabsch_rmsd(x, y, mask=mask)[0]) < 1e-4


class TestMetrics:
    def test_rmsd_golden(self):
        x = jnp.zeros((1, 10, 3))
        y = jnp.ones((1, 10, 3))  # per-point distance sqrt(3), rmsd = 1.0
        assert np.isclose(float(geo.rmsd(x, y)[0]), 1.0, atol=1e-6)

    def test_gdt_perfect_and_modes(self):
        x = jax.random.normal(jax.random.PRNGKey(11), (2, 16, 3))
        assert np.allclose(geo.gdt(x, x), 1.0)
        assert np.allclose(geo.gdt(x, x, mode="HA"), 1.0)
        y = x + jnp.array([100.0, 0, 0])
        assert np.allclose(geo.gdt(x, y), 0.0)

    def test_gdt_halfway(self):
        # distances of 3A: inside cutoffs 4,8 but not 1,2 -> GDT_TS = 0.5
        x = jnp.zeros((1, 8, 3))
        y = x.at[..., 0].add(3.0)
        assert np.isclose(float(geo.gdt(x, y)[0]), 0.5, atol=1e-6)

    def test_tm_score(self):
        x = jax.random.normal(jax.random.PRNGKey(12), (2, 32, 3))
        assert np.allclose(geo.tm_score(x, x), 1.0, atol=1e-6)
        y = x + jnp.array([1000.0, 0, 0])
        assert float(geo.tm_score(x, y).max()) < 1e-3

    def test_lddt_perfect(self):
        x = jax.random.normal(jax.random.PRNGKey(13), (1, 24, 3)) * 4
        scores = geo.lddt_ca(x, x)
        assert scores.shape == (1, 24)
        assert np.allclose(scores, 1.0, atol=1e-6)

    def test_lddt_degrades(self):
        x = jax.random.normal(jax.random.PRNGKey(14), (1, 24, 3)) * 4
        y = x + jax.random.normal(jax.random.PRNGKey(15), x.shape) * 3.0
        scores = geo.lddt_ca(x, y)
        assert float(scores.mean()) < 0.9

    def test_lddt_mask(self):
        x = jax.random.normal(jax.random.PRNGKey(16), (1, 24, 3)) * 4
        mask = jnp.ones((1, 24), dtype=bool).at[:, -6:].set(False)
        scores = geo.lddt_ca(x, x, mask=mask)
        assert (scores[:, -6:] == 0).all()

    def test_distmat_loss(self):
        x = jax.random.normal(jax.random.PRNGKey(17), (8, 3))
        assert np.isclose(float(geo.distmat_loss(x, x)), 0.0, atol=1e-9)
        y = jax.random.normal(jax.random.PRNGKey(18), (8, 3))
        assert float(geo.distmat_loss(x, y)) > 0


class TestQuaternion:
    def test_identity(self):
        q = quat.identity_quaternion((2, 5))
        r = quat.quaternion_to_matrix(q)
        assert np.allclose(r, np.broadcast_to(np.eye(3), (2, 5, 3, 3)))

    def test_multiply_matches_matrix_product(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(19))
        q1 = jax.random.normal(k1, (4,))
        q2 = jax.random.normal(k2, (4,))
        q1 = q1 / jnp.linalg.norm(q1)
        q2 = q2 / jnp.linalg.norm(q2)
        r = quat.quaternion_to_matrix(quat.quaternion_multiply(q1, q2))
        r_ref = quat.quaternion_to_matrix(q1) @ quat.quaternion_to_matrix(q2)
        assert np.allclose(r, r_ref, atol=1e-5)

    def test_rotation_is_orthonormal(self):
        q = jax.random.normal(jax.random.PRNGKey(20), (7, 4))
        r = quat.quaternion_to_matrix(q)
        eye = jnp.einsum("...ij,...kj->...ik", r, r)
        assert np.allclose(eye, np.broadcast_to(np.eye(3), (7, 3, 3)),
                           atol=1e-5)
        assert np.allclose(jnp.linalg.det(r), 1.0, atol=1e-5)


class TestRigid:
    def test_apply_invert_roundtrip(self):
        key = jax.random.PRNGKey(21)
        q = jax.random.normal(key, (2, 6, 4))
        t = jax.random.normal(jax.random.PRNGKey(22), (2, 6, 3))
        frames = Rigid(q, t)
        pts = jax.random.normal(jax.random.PRNGKey(23), (2, 6, 5, 3))
        back = frames.invert_apply(frames.apply(pts))
        assert np.allclose(back, pts, atol=1e-4)

    def test_identity_is_noop(self):
        frames = Rigid.identity((1, 3))
        pts = jax.random.normal(jax.random.PRNGKey(24), (1, 3, 4, 3))
        assert np.allclose(frames.apply(pts), pts, atol=1e-6)

    def test_compose_update_identity(self):
        frames = Rigid.identity((1, 3))
        dq = quat.identity_quaternion((1, 3))
        dt = jnp.zeros((1, 3, 3))
        new = frames.compose_update(dq, dt)
        assert np.allclose(new.quaternions, frames.quaternions)
        assert np.allclose(new.translations, frames.translations)


class TestPhis:
    def test_fraction_negative(self):
        # helix-like synthetic backbone: deterministic output in [0, 1]
        key = jax.random.PRNGKey(25)
        nc = jax.random.normal(key, (2, 10, 3))
        ca = nc + 0.5
        cc = nc - 0.5
        frac = geo.fraction_negative_phis(nc, ca, cc)
        assert frac.shape == (2,)
        assert ((frac >= 0) & (frac <= 1)).all()


@pytest.mark.parametrize("table,shape", [
    (constants.CLOUD_MASK_TABLE, (21, 14)),
    (constants.ATOM_ID_TABLE, (21, 14)),
    (constants.BOND_ADJACENCY_TABLE, (21, 14, 14)),
])
def test_constant_tables(table, shape):
    assert table.shape == shape


def test_glycine_has_no_sidechain():
    g = constants.AA_ALPHABET.index("G")
    assert constants.CLOUD_MASK_TABLE[g].sum() == 4  # backbone only


def test_padding_token_empty():
    pad = constants.AA_ALPHABET.index("_")
    assert constants.CLOUD_MASK_TABLE[pad].sum() == 0
    assert constants.BOND_ADJACENCY_TABLE[pad].sum() == 0
