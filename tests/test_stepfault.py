"""Step-loop fault-domain tests (ISSUE 14): carry checkpointing +
resume-at-age recovery (byte-equality to the uninterrupted loop,
bounded recycles_lost, watchdog-rebuild resume), per-row poison
isolation (raise-mode attribution, the per-step non-finite scan,
quarantine persistence, the knob-off bisection fallback), step-aware +
featurize chaos sites, lease safety on every failure path (idempotent
release, the acquire->handoff audit), the checkpoint-off scrubbed-stats
identity pin, and the loadtest flag surface.

Scheduler tests run against scripted step-capable stubs (no XLA) so the
failure SCHEDULING is under test — same discipline as
tests/test_resilience.py; real-executor coverage (resume byte-equality,
mesh-lease isolation) rides the tiny Alphafold2 config from
tests/test_continuous.py.
"""

import json
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_requests
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, FaultInjected, FaultPlan,
                                  FeaturePool, FoldExecutor, FoldRequest,
                                  MeshPolicy, PipelineScheduler,
                                  RawFoldRequest, RecyclePolicy,
                                  RetryPolicy, Scheduler, SchedulerConfig,
                                  ServeMetrics, TransientExecutorError)
from alphafold2_tpu.serve.meshpolicy import DeviceSliceAllocator

MSA_DEPTH = 3


# -- scripted step-capable executor -----------------------------------


class _StepStub:
    """Step/admission-capable scripted executor (the _ContStub shape
    from tests/test_continuous.py) with fault scripting: transient
    raises at chosen recycle indices, content-addressed raise-mode
    poison with row attribution (the FaultInjected.rows contract),
    NaN-mode poison rows, and a one-shot sleep for the watchdog path.
    Coords are a pure function of each row's step count, so a resumed
    loop must reproduce the uninterrupted run exactly."""

    def __init__(self, fail_at=None, poison_token=None,
                 poison_mode="raise", nan_from_age=1, sleep_at=None,
                 sleep_s=0.0, calls=None, step_s=0.005,
                 poison_sites=("init", "init_rows", "step")):
        self.fail_at = dict(fail_at or {})   # recycle -> raises left
        self.poison_token = poison_token
        self.poison_mode = poison_mode
        self.poison_sites = tuple(poison_sites)
        self.nan_from_age = nan_from_age
        self.sleep_at = dict(sleep_at or {})  # recycle -> sleeps left
        self.sleep_s = sleep_s
        self.calls = calls if calls is not None else []
        self.step_s = step_s
        self.reached = threading.Event()
        self.release = threading.Event()
        self.gate_at = None
        self._lock = threading.Lock()

    # - fault scripting -

    def _poison_rows(self, batch):
        if self.poison_token is None or self.poison_mode != "raise":
            return []
        seq = np.asarray(batch["seq"])
        mask = np.asarray(batch["mask"])
        return [i for i in range(seq.shape[0])
                if mask[i].any() and seq[i, 0] == self.poison_token]

    def _maybe_poison(self, batch, site):
        if site not in self.poison_sites:
            return
        rows = self._poison_rows(batch)
        if rows:
            exc = FaultInjected(
                f"poison_input: scripted failure rows {rows} at {site}")
            exc.rows = rows
            raise exc

    # - executor surface -

    def _mk_state(self, ids, counts, b, n):
        coords = np.zeros((b, n, 3), np.float32)
        for i, c in enumerate(counts):
            coords[i] = float(c)
        if self.poison_token is not None and self.poison_mode == "nan":
            for i in range(b):
                if ids[i] == self.poison_token \
                        and counts[i] >= self.nan_from_age:
                    coords[i] = np.nan
        return SimpleNamespace(
            coords=coords,
            confidence=np.zeros((b, n), np.float32),
            recyclables=None, ids=np.array(ids), counts=np.array(counts))

    def run_init(self, batch, trace=None, devices=None,
                 mesh_shape=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        with self._lock:
            self.calls.append(("init", [int(i) for i in seq[:, 0]]))
        self._maybe_poison(batch, "init")
        return self._mk_state(seq[:, 0], [0] * b, b, n)

    def run_init_rows(self, batch, state, row_mask, trace=None,
                      devices=None, mesh_shape=None, span_attrs=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        mask = np.asarray(row_mask)
        with self._lock:
            self.calls.append(
                ("init_rows", [int(i) for i in seq[:, 0][mask]]))
        self._maybe_poison(batch, "init_rows")
        ids = state.ids.copy()
        counts = state.counts.copy()
        ids[mask] = seq[:, 0][mask]
        counts[mask] = 0
        return self._mk_state(ids, counts, b, n)

    def run_step(self, batch, state, recycle_index, trace=None,
                 devices=None, mesh_shape=None, span_attrs=None):
        b, n = np.asarray(batch["seq"]).shape
        with self._lock:
            self.calls.append(("step", int(recycle_index)))
            gated = self.gate_at is not None \
                and recycle_index == self.gate_at
            if gated:
                self.gate_at = None
        if gated:
            self.reached.set()
            assert self.release.wait(timeout=60)
        self._maybe_poison(batch, "step")
        with self._lock:
            if self.fail_at.get(int(recycle_index), 0) > 0:
                self.fail_at[int(recycle_index)] -= 1
                raise TransientExecutorError(
                    f"scripted transient at recycle {recycle_index}")
            slept = self.sleep_at.get(int(recycle_index), 0) > 0
            if slept:
                self.sleep_at[int(recycle_index)] -= 1
        if slept:
            time.sleep(self.sleep_s)
        counts = [int(c) + 1 for c in state.counts]
        time.sleep(self.step_s)
        return self._mk_state(state.ids, counts, b, n)

    def run(self, batch, num_recycles, **kw):        # opaque fallback
        st = self.run_init(batch)
        for r in range(1, num_recycles + 1):
            st = self.run_step(batch, st, r)
        return SimpleNamespace(coords=st.coords,
                               confidence=st.confidence)

    def stats(self):
        return {"calls": len(self.calls)}


def _stub_sched(stub, num_recycles, policy=None, retry=None, max_batch=2,
                buckets=(32,), **kw):
    kw.setdefault("metrics", ServeMetrics(registry=MetricsRegistry()))
    kw.setdefault("registry", MetricsRegistry())
    policy = policy or RecyclePolicy(converge_tol=0.0)
    return Scheduler(
        stub, BucketPolicy(buckets),
        SchedulerConfig(max_batch_size=max_batch, max_wait_ms=5.0,
                        num_recycles=num_recycles, msa_depth=0,
                        poll_ms=2.0),
        recycle_policy=policy, retry=retry, **kw)


def _req(token, length=12, **kw):
    return FoldRequest(seq=np.full(length, token, np.int32), **kw)


def _retry(**kw):
    kw.setdefault("max_attempts", 4)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("jitter", 0.0)
    return RetryPolicy(**kw)


def _mk_batch(tokens, length=16, max_batch=2):
    reqs = [_req(t, length=length - 4) for t in tokens]
    return BucketPolicy((length,)).assemble(reqs, length, max_batch)[0]


# -- units ------------------------------------------------------------


@pytest.mark.quick
class TestKnobUnits:
    def test_retry_policy_defaults_off_and_validated(self):
        rp = RetryPolicy()
        assert rp.checkpoint_every == 0
        assert rp.row_isolation is False
        with pytest.raises(ValueError):
            RetryPolicy(checkpoint_every=-1)

    def test_fault_plan_rate_validation(self):
        with pytest.raises(ValueError):
            FaultPlan(step_fail_at={1: 1.5})
        with pytest.raises(ValueError):
            FaultPlan(featurize_error_rate=2.0)


class TestStepAwareFaultPlan:
    def test_step_fail_at_hits_specific_recycle_only(self):
        plan = FaultPlan(seed=7, step_fail_at={1: 1.0}).arm()
        batch = _mk_batch([3])
        plan.on_executor_run(batch, variant="step", recycle=0)
        plan.on_executor_run(batch, variant="init")
        plan.on_executor_run(batch, variant="fold")
        with pytest.raises(TransientExecutorError):
            plan.on_executor_run(batch, variant="step", recycle=1)
        snap = plan.snapshot()
        assert snap["injected"]["step_fail"] == 1
        assert snap["step_fail_at"] == {1: 1.0}
        assert snap["injected_by_variant"] == {"step": {"step_fail": 1}}

    def test_counts_tagged_by_executing_variant(self):
        plan = FaultPlan(seed=0, exec_error_rate=1.0).arm()
        batch = _mk_batch([3])
        for variant in ("init", "step", "init_rows"):
            with pytest.raises(TransientExecutorError):
                plan.on_executor_run(batch, variant=variant, recycle=1)
        per = plan.snapshot()["injected_by_variant"]
        assert set(per) == {"init", "step", "init_rows"}
        assert all(v == {"exec_error": 1} for v in per.values())

    def test_poison_raise_attributes_batch_rows(self):
        plan = FaultPlan(seed=0).arm()
        poison = _req(9, length=12)
        plan.add_poison(np.asarray(poison.seq), mode="raise")
        batch = _mk_batch([3, 9])
        with pytest.raises(FaultInjected) as ei:
            plan.on_executor_run(batch, variant="step", recycle=2)
        assert ei.value.rows == [1]


class TestFeaturizeFaults:
    def test_featurize_error_fans_out_without_wedging(self):
        """An injected featurize failure resolves the leader AND every
        coalesced waiter as error; disarming the plan afterwards, the
        SAME pool serves fresh work — nothing wedged."""
        reg = MetricsRegistry()
        plan = FaultPlan(seed=0, featurize_error_rate=1.0,
                         registry=reg).arm()
        pool = FeaturePool(workers=1, latency_s=0.05, faults=plan,
                           registry=reg)
        sched = _stub_sched(_StepStub(), 1, registry=reg)
        seq = "MKVLAARNDC"
        with PipelineScheduler(sched, pool) as pipe:
            tickets = [pipe.submit_raw(RawFoldRequest(seq))
                       for _ in range(3)]
            resps = [t.result(timeout=30) for t in tickets]
            assert all(r.status == "error" for r in resps)
            assert all("featurize" in r.error for r in resps)
            plan.disarm()
            ok = pipe.submit_raw(
                RawFoldRequest(seq)).result(timeout=30)
        assert ok.ok
        assert plan.snapshot()["injected"]["featurize_error"] >= 1
        assert pool.snapshot()["errors"] == 3

    def test_featurize_latency_exercises_deadline_path(self):
        plan = FaultPlan(seed=0, featurize_latency_rate=1.0,
                         featurize_latency_s=0.2).arm()
        reg = MetricsRegistry()
        pool = FeaturePool(workers=1, faults=plan, registry=reg)
        sched = _stub_sched(_StepStub(), 1, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            resp = pipe.submit_raw(RawFoldRequest(
                "MKVLAARNDC", deadline_s=0.02)).result(timeout=30)
        assert resp.status == "shed"
        assert "feature_deadline_exceeded" in resp.error
        assert plan.snapshot()["injected"]["featurize_latency"] == 1


# -- carry checkpointing / resume-at-age ------------------------------


class TestCheckpointResume:
    def test_transient_resumes_at_checkpointed_age(self):
        """checkpoint_every=1 + a one-shot transient at recycle 2: the
        loop resumes at the checkpoint (zero recycles lost), never
        requeues to zero (exactly one init), every ticket ok with the
        coords an uninterrupted run produces, and the breaker stays
        closed — the successful resume IS the health proof."""
        stub = _StepStub(fail_at={2: 1})
        sched = _stub_sched(stub, 3, retry=_retry(checkpoint_every=1,
                                                  breaker_threshold=2))
        sched.start()
        try:
            t1, t2 = sched.submit(_req(1)), sched.submit(_req(2))
            r1, r2 = t1.result(timeout=60), t2.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r2.ok
        assert r1.recycles == 3 and r2.recycles == 3
        # coords are the step count: an uninterrupted 3-recycle run
        np.testing.assert_array_equal(r1.coords,
                                      np.full((12, 3), 3.0, np.float32))
        res = sched.serve_stats()["resilience"]
        assert res["checkpoint_resumes"] == 1
        assert res["recycles_lost"] == 0
        assert res["checkpoints"] >= 3
        assert res["breaker"]["state"] == "closed"
        inits = [c for c in stub.calls if c[0] == "init"]
        assert len(inits) == 1                 # never restarted at zero
        # the failed attempt re-executed exactly once: steps 1,2,2,3
        assert [c[1] for c in stub.calls if c[0] == "step"] \
            == [1, 2, 2, 3]

    def test_checkpoint_cadence_bounds_progress_loss(self):
        """checkpoint_every=2 with the failure two steps past the
        checkpoint: exactly the steps since the checkpoint re-execute
        (recycles_lost == 1 <= checkpoint_every), never the whole
        loop."""
        stub = _StepStub(fail_at={4: 1})
        sched = _stub_sched(stub, 5, retry=_retry(checkpoint_every=2))
        sched.start()
        try:
            r = sched.submit(_req(1)).result(timeout=60)
        finally:
            sched.stop()
        assert r.ok and r.recycles == 5
        res = sched.serve_stats()["resilience"]
        assert res["checkpoint_resumes"] == 1
        assert 0 < res["recycles_lost"] <= 2
        assert res["recycles_lost"] == 1       # ckpt at r=2, fail at 4
        assert [c[1] for c in stub.calls if c[0] == "step"] \
            == [1, 2, 3, 4, 3, 4, 5]

    def test_checkpoint_off_requeues_to_zero(self):
        """The off switch: the same transient without checkpoint_every
        takes the PR-5 path — survivors requeue and restart at recycle
        0 (a second init), and serve_stats carries NO ISSUE-14 keys."""
        stub = _StepStub(fail_at={2: 1})
        sched = _stub_sched(stub, 3, retry=_retry())
        sched.start()
        try:
            r = sched.submit(_req(1)).result(timeout=60)
        finally:
            sched.stop()
        assert r.ok and r.recycles == 3
        res = sched.serve_stats()["resilience"]
        assert "checkpoint_resumes" not in res
        assert "recycles_lost" not in res
        assert res["retries"] == 1
        inits = [c for c in stub.calls if c[0] == "init"]
        assert len(inits) == 2                 # restarted from zero
        assert [c[1] for c in stub.calls if c[0] == "step"] \
            == [1, 2, 1, 2, 3]

    def test_restore_failure_falls_back_to_requeue(self):
        """Checkpoint restore trouble must never hang a ticket: the
        recovery degrades to the classic requeue-to-zero path — a
        second init, retries counted, zero resumes claimed."""
        stub = _StepStub(fail_at={2: 1})
        sched = _stub_sched(stub, 3, retry=_retry(checkpoint_every=1))
        orig = sched._batch_from_host
        boom = {"left": 1}

        def flaky(host):
            if boom["left"]:
                boom["left"] -= 1
                raise RuntimeError("restore trouble")
            return orig(host)

        sched._batch_from_host = flaky
        sched.start()
        try:
            r = sched.submit(_req(1)).result(timeout=60)
        finally:
            sched.stop()
        assert r.ok and r.recycles == 3
        res = sched.serve_stats()["resilience"]
        assert res["checkpoint_resumes"] == 0
        assert res["retries"] == 1
        assert len([c for c in stub.calls if c[0] == "init"]) == 2

    def test_watchdog_fire_rebuilds_then_resumes(self):
        """A mid-loop hang: the watchdog fires, the executor is
        REBUILT via executor_factory, and the resumed loop continues
        on the fresh executor from the checkpointed ages — one init
        total across both executors."""
        calls = []
        stub = _StepStub(sleep_at={2: 1}, sleep_s=1.5, calls=calls)
        factory = lambda: _StepStub(calls=calls)       # noqa: E731
        sched = _stub_sched(stub, 3,
                            retry=_retry(checkpoint_every=1,
                                         watchdog_s=0.2),
                            executor_factory=factory)
        sched.start()
        try:
            r = sched.submit(_req(1)).result(timeout=60)
        finally:
            sched.stop()
        assert r.ok and r.recycles == 3
        res = sched.serve_stats()["resilience"]
        assert res["watchdog_fires"] == 1
        assert res["executor_rebuilds"] == 1
        assert res["checkpoint_resumes"] == 1
        assert len([c for c in calls if c[0] == "init"]) == 1

    def test_resume_byte_equal_uninterrupted_real_executor(self):
        """ISSUE-14 acceptance at the numerics level: a REAL fold
        interrupted by a transient at recycle 2 under checkpoint_every=1
        serves final coords BYTE-equal to the fault-free run."""
        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                           predict_coords=True,
                           structure_module_depth=1)
        n = 16
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
            msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
            mask=jnp.ones((1, n), bool),
            msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))

        class OneShotFail(FoldExecutor):
            fired = False

            def run_step(self, batch, state, recycle_index, **kw):
                if not OneShotFail.fired and recycle_index == 2:
                    OneShotFail.fired = True
                    raise TransientExecutorError("scripted mid-loop")
                return super().run_step(batch, state, recycle_index,
                                        **kw)

        req = synthetic_requests(jax.random.PRNGKey(3), num=1,
                                 lengths=(12,), msa_depth=MSA_DEPTH)[0]

        def run_one(ex_cls, retry):
            ex = ex_cls(model, params, max_entries=8)
            sched = Scheduler(
                ex, BucketPolicy((16,)),
                SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                                num_recycles=3, msa_depth=MSA_DEPTH),
                recycle_policy=RecyclePolicy(converge_tol=0.0),
                retry=retry, metrics=ServeMetrics(
                    registry=MetricsRegistry()),
                registry=MetricsRegistry())
            with sched:
                r = sched.submit(FoldRequest(
                    seq=req.seq, msa=req.msa)).result(timeout=300)
            return r, sched

        faulted, sched = run_one(OneShotFail,
                                 _retry(checkpoint_every=1))
        clean, _ = run_one(FoldExecutor, None)
        assert OneShotFail.fired
        assert faulted.ok and clean.ok, (faulted.error, clean.error)
        res = sched.serve_stats()["resilience"]
        assert res["checkpoint_resumes"] == 1
        assert res["recycles_lost"] == 0
        np.testing.assert_array_equal(faulted.coords, clean.coords)
        np.testing.assert_array_equal(faulted.confidence,
                                      clean.confidence)


# -- per-row poison isolation -----------------------------------------


class TestRowIsolation:
    def test_raise_mode_poison_retires_only_offending_row(self):
        """A row-attributed deterministic failure mid-loop quarantines
        and retires exactly the poison row; its batch mate never leaves
        the loop (one init, no bisection), the freed row refills via
        continuous admission, and a later duplicate of the poison fails
        fast with ZERO executor calls."""
        stub = _StepStub(poison_token=9, poison_sites=("step",))
        stub.gate_at = 2
        sched = _stub_sched(
            stub, 4, policy=RecyclePolicy(converge_tol=0.0,
                                          continuous=True),
            retry=_retry(row_isolation=True))
        sched.start()
        try:
            t1 = sched.submit(_req(1))
            tp = sched.submit(_req(9))
            assert stub.reached.wait(timeout=60)
            t3 = sched.submit(_req(3))           # pending mid-loop
            time.sleep(0.05)
            stub.release.set()
            r1 = t1.result(timeout=60)
            rp = tp.result(timeout=60)
            r3 = t3.result(timeout=60)
            calls_before = len(stub.calls)
            rdup = sched.submit(_req(9)).result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r1.recycles == 4
        assert rp.status == "poisoned"
        assert "row-attributed" in rp.error
        # the innocent survivor's result is byte-equal to a fault-free
        # run (coords == its own step count everywhere)
        np.testing.assert_array_equal(r1.coords,
                                      np.full((12, 3), 4.0, np.float32))
        # the freed row served the pending fold like any early exit
        assert r3.ok and r3.recycles == 4
        res = sched.serve_stats()["resilience"]
        assert res["row_poison_isolations"] == 1
        assert res["bisections"] == 0
        assert len([c for c in stub.calls if c[0] == "init"]) == 1
        assert ("init_rows", [3]) in stub.calls
        # quarantine fail-fast: no executor work for the duplicate
        assert rdup.status == "poisoned"
        assert len(stub.calls) == calls_before

    def test_nonfinite_scan_isolates_row_midloop(self):
        """The per-step non-finite scan: a row whose output goes NaN at
        age 1 retires THAT step as poisoned (threshold 1) while its
        batch mate runs to full depth untouched."""
        stub = _StepStub(poison_token=9, poison_mode="nan",
                         nan_from_age=1)
        sched = _stub_sched(
            stub, 4, retry=_retry(row_isolation=True,
                                  nan_poison_threshold=1))
        sched.start()
        try:
            t1 = sched.submit(_req(1))
            tp = sched.submit(_req(9))
            r1 = t1.result(timeout=60)
            rp = tp.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r1.recycles == 4
        np.testing.assert_array_equal(r1.coords,
                                      np.full((12, 3), 4.0, np.float32))
        assert rp.status == "poisoned"
        assert "nonfinite" in rp.error
        res = sched.serve_stats()["resilience"]
        assert res["row_poison_isolations"] == 1
        assert res["nonfinite_outputs"] == 1
        # isolation happened at the FIRST bad step, not at retirement:
        # the loop ran its full 4 steps exactly once
        assert [c[1] for c in stub.calls if c[0] == "step"] \
            == [1, 2, 3, 4]

    def test_knob_off_falls_back_to_bisection(self):
        """Without row_isolation the same attributed failure takes the
        PR-5 path: the cohort leaves the loop and bisection converges
        on the poison (extra executions), innocents still ok."""
        stub = _StepStub(poison_token=9)
        sched = _stub_sched(stub, 2, retry=_retry())
        sched.start()
        try:
            t1 = sched.submit(_req(1))
            tp = sched.submit(_req(9))
            r1 = t1.result(timeout=60)
            rp = tp.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok
        assert rp.status == "poisoned"
        res = sched.serve_stats()["resilience"]
        assert "row_poison_isolations" not in res
        assert res["bisections"] >= 1
        assert len([c for c in stub.calls if c[0] == "init"]) > 1

    def test_quarantine_strike_persists_via_path(self, tmp_path):
        """A row-isolation quarantine written to quarantine_path
        survives a restart: the next scheduler fails the poison fast
        with zero executor calls."""
        qpath = str(tmp_path / "quarantine.jsonl")
        stub = _StepStub(poison_token=9)
        sched = _stub_sched(stub, 2, retry=_retry(row_isolation=True),
                            quarantine_path=qpath)
        sched.start()
        try:
            tp = sched.submit(_req(9))
            t1 = sched.submit(_req(1))
            assert tp.result(timeout=60).status == "poisoned"
            assert t1.result(timeout=60).ok
        finally:
            sched.stop()
        stub2 = _StepStub()
        sched2 = _stub_sched(stub2, 2,
                             retry=_retry(row_isolation=True),
                             quarantine_path=qpath)
        sched2.start()
        try:
            r = sched2.submit(_req(9)).result(timeout=60)
        finally:
            sched2.stop()
        assert r.status == "poisoned"
        assert stub2.calls == []               # zero executor calls

    @pytest.mark.skipif(len(jax.devices()) < 2,
                        reason="needs >= 2 devices")
    def test_mesh_lease_isolation_innocent_byte_equal(self):
        """Raise-mode poison on a 1x2 mesh lease: the poison row is
        isolated through the real FaultPlan attribution, the innocent
        batch mate serves coords byte-equal to folding alone on the
        same mesh, and the slice comes back (allocator occupancy 0)."""
        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                           predict_coords=True,
                           structure_module_depth=1)
        n = 16
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
            msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
            mask=jnp.ones((1, n), bool),
            msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
        a, p = synthetic_requests(jax.random.PRNGKey(5), num=2,
                                  lengths=(12, 10),
                                  msa_depth=MSA_DEPTH)

        def mk(faults, retry):
            ex = FoldExecutor(model, params, max_entries=8,
                              faults=faults)
            sched = Scheduler(
                ex, BucketPolicy((16,)),
                SchedulerConfig(max_batch_size=2, max_wait_ms=20.0,
                                num_recycles=2, msa_depth=MSA_DEPTH),
                recycle_policy=RecyclePolicy(converge_tol=0.0),
                retry=retry,
                mesh_policy=MeshPolicy({16: 2},
                                       devices=jax.devices()[:2]),
                metrics=ServeMetrics(registry=MetricsRegistry()),
                registry=MetricsRegistry())
            return sched

        plan = FaultPlan(seed=0)
        plan.add_poison(np.asarray(p.seq), mode="raise")
        sched = mk(plan, _retry(row_isolation=True))
        sched.warmup()
        plan.arm()
        sched.start()
        try:
            ta = sched.submit(FoldRequest(seq=a.seq, msa=a.msa))
            tp = sched.submit(FoldRequest(seq=p.seq, msa=p.msa))
            ra = ta.result(timeout=300)
            rp = tp.result(timeout=300)
        finally:
            sched.stop()
        assert ra.ok, ra.error
        assert rp.status == "poisoned"
        stats = sched.serve_stats()
        assert stats["resilience"]["row_poison_isolations"] >= 1
        assert stats["mesh"]["allocator"]["busy_devices"] == 0
        alone = mk(None, None)
        alone.warmup()
        with alone:
            ra2 = alone.submit(
                FoldRequest(seq=a.seq, msa=a.msa)).result(timeout=300)
        np.testing.assert_array_equal(ra.coords, ra2.coords)
        np.testing.assert_array_equal(ra.confidence, ra2.confidence)


# -- lease safety -----------------------------------------------------


class TestLeaseSafety:
    def test_release_idempotent_and_span_reacquire_rearm(self):
        """The SliceLease.held contract: double release is a no-op
        (never frees a span someone else now holds), and acquire_span
        re-arms the SAME object so every finally-block reference
        releases what is actually leased."""
        alloc = DeviceSliceAllocator(list(range(4)))
        lease = alloc.acquire((1, 2))
        assert lease is not None and lease.held
        alloc.release(lease)
        assert not lease.held and alloc.busy_devices == 0
        # double release: no-op even after the span is re-leased
        other = alloc.acquire((1, 2))
        alloc.release(lease)
        assert alloc.busy_devices == 2          # other's span survives
        alloc.release(other)
        # a preemption-style yield + blocking re-acquire re-arms the
        # same lease object
        lease2 = alloc.acquire((1, 2))
        alloc.release(lease2)
        back = alloc.acquire_span(lease2)
        assert back is lease2 and lease2.held
        alloc.release(lease2)
        assert alloc.busy_devices == 0

    @pytest.mark.skipif(len(jax.devices()) < 2,
                        reason="needs >= 2 devices")
    def test_midloop_failures_never_leak_slice(self):
        """The ISSUE-14 audit regression: after a transient-with-resume
        loop AND a hard (unclassified) mid-loop failure on a leased
        slice, allocator occupancy returns to zero."""
        # transient + checkpoint resume on the lease
        stub = _StepStub(fail_at={1: 1})
        sched = _stub_sched(
            stub, 2, retry=_retry(checkpoint_every=1),
            mesh_policy=MeshPolicy({32: 2}, devices=jax.devices()[:2]))
        sched.start()
        try:
            r = sched.submit(_req(1)).result(timeout=60)
        finally:
            sched.stop()
        assert r.ok
        assert sched.serve_stats()["resilience"][
            "checkpoint_resumes"] == 1
        assert sched._allocator.busy_devices == 0
        # hard failure, no retry policy: tickets error, slice back
        stub2 = _StepStub()
        stub2.fail_hard = True

        def boom(*a, **k):
            raise ValueError("hard mid-loop failure")

        stub2.run_step = boom
        sched2 = _stub_sched(
            stub2, 2,
            mesh_policy=MeshPolicy({32: 2}, devices=jax.devices()[:2]))
        sched2.start()
        try:
            r2 = sched2.submit(_req(1)).result(timeout=60)
        finally:
            sched2.stop()
        assert r2.status == "error"
        assert sched2._allocator.busy_devices == 0

    @pytest.mark.skipif(len(jax.devices()) < 2,
                        reason="needs >= 2 devices")
    def test_dispatch_bookkeeping_failure_releases_slice(self):
        """An exception between allocator acquire and the pool handoff
        (the audited window) releases the lease and still folds the
        batch inline — no stranded slice, no lost ticket."""
        stub = _StepStub()
        sched = _stub_sched(
            stub, 2,
            mesh_policy=MeshPolicy({32: 2}, devices=jax.devices()[:2]))
        fired = []
        orig = sched._set_busy_gauge

        def flaky():
            if not fired:
                fired.append(1)
                raise RuntimeError("gauge trouble")
            return orig()

        sched._set_busy_gauge = flaky
        sched.start()
        try:
            r = sched.submit(_req(1)).result(timeout=60)
        finally:
            sched.stop()
        assert fired
        assert r.ok
        assert sched._allocator.busy_devices == 0


# -- off-by-default identity ------------------------------------------


class TestOffIdentity:
    def test_knobless_retry_scrubbed_stats_and_metric_names_identical(
            self):
        """`retry=` without the ISSUE-14 knobs is byte-for-byte the
        PR-5 surface: scrubbed serve_stats() identical to a policy
        that never mentioned the fields, and the metric-name set
        contains none of the new counters (they are minted only when a
        knob is on)."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        def run_one(retry):
            reg = MetricsRegistry()
            sched = _stub_sched(_StepStub(), 2, retry=retry,
                                registry=reg,
                                metrics=ServeMetrics(registry=reg))
            with sched:
                for tok in (1, 2, 3):
                    assert sched.submit(_req(tok)).result(
                        timeout=60).ok
            return scrub(sched.serve_stats()), set(reg.snapshot())

        explicit_off, names_off = run_one(
            RetryPolicy(max_attempts=3, jitter=0.0,
                        checkpoint_every=0, row_isolation=False))
        never_heard, names_base = run_one(
            RetryPolicy(max_attempts=3, jitter=0.0))
        assert json.dumps(explicit_off, sort_keys=True, default=str) \
            == json.dumps(never_heard, sort_keys=True, default=str)
        assert names_off == names_base
        new = {"serve_checkpoint_resumes_total",
               "serve_recycles_lost_total",
               "serve_row_poison_isolations_total"}
        assert not (new & names_base)
        # ... and flipping a knob on mints them
        reg = MetricsRegistry()
        _stub_sched(_StepStub(), 2,
                    retry=_retry(checkpoint_every=1,
                                 row_isolation=True),
                    registry=reg, metrics=ServeMetrics(registry=reg))
        assert new <= set(reg.snapshot())


# -- loadtest flag surface --------------------------------------------


class TestLoadtestFlags:
    def test_stepfault_flags_fast(self, tmp_path, capsys):
        """Tier-1 flag-rot tripwire: --chaos-step-at /
        --checkpoint-every / --row-isolation compose with --continuous
        on a real (tiny) run, and the report carries the recovery-cost
        fields."""
        import sys
        sys.path.insert(0, "tools")
        try:
            import serve_loadtest
        finally:
            sys.path.pop(0)
        rc = serve_loadtest.main([
            "--requests", "8", "--concurrency", "4",
            "--lengths", "12", "--buckets", "16",
            "--msa-depth", str(MSA_DEPTH), "--max-batch", "2",
            "--max-wait-ms", "5", "--num-recycles", "2",
            "--continuous", "--dim", "32", "--depth", "1",
            "--chaos", "--chaos-exec-rate", "0.0",
            "--chaos-step-at", "1=0.25", "--checkpoint-every", "1",
            "--row-isolation", "--retry", "on",
            "--metrics-path", str(tmp_path / "m.jsonl")])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip()
                            .splitlines()[-1])
        assert "checkpoint_resumes" in report
        assert "recycles_lost" in report
        assert "row_poison_isolations" in report
        assert report["chaos"]["step_fail_at"] == {"1": 0.25}
        assert report["resilience"]["checkpoint_every"] == 1
        assert report["resilience"]["row_isolation"] is True
        # the raise-mode poison sentinel was isolated or bisected to
        # quarantine either way — never an innocent casualty
        assert report["poisoned"] == 1
