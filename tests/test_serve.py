"""Serving subsystem tests (ISSUE 1): bucketing determinism, executor
cache accounting, scheduler batch formation / deadline shedding /
backpressure, and the end-to-end mixed-length acceptance demo on CPU.

Also covers the satellite stats plumbing the server reports through:
profiling.percentile / StepTimer p90/p99 and MetricsLogger flush().
"""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2, obs
from alphafold2_tpu.data.synthetic import synthetic_requests
from alphafold2_tpu.serve import (BucketPolicy, FoldExecutor, FoldRequest,
                                  QueueFullError, Scheduler,
                                  SchedulerConfig, ServeMetrics)
from alphafold2_tpu.utils.logging import MetricsLogger
from alphafold2_tpu.utils.profiling import StepTimer, percentile

MSA_DEPTH = 3


@pytest.fixture(scope="module")
def model_and_params():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1)
    n = 16
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
        mask=jnp.ones((1, n), bool),
        msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
    return model, params


def requests_of(lengths, key=1, msa_depth=MSA_DEPTH, **kwargs):
    reqs = synthetic_requests(jax.random.PRNGKey(key), num=len(lengths),
                              lengths=lengths, msa_depth=msa_depth)
    for r in reqs:
        for k, v in kwargs.items():
            setattr(r, k, v)
    return reqs


@pytest.mark.quick
class TestBucketPolicy:
    def test_powers_of_two_edges(self):
        p = BucketPolicy.powers_of_two(32, 512)
        assert p.edges == (32, 64, 128, 256, 512)
        assert BucketPolicy.powers_of_two(32, 96).edges == (32, 64, 96)

    def test_mapping_deterministic_and_minimal(self):
        p = BucketPolicy((16, 32, 48))
        for n in range(1, 49):
            b = p.bucket_for(n)
            assert b == p.bucket_for(n)          # same length, same shape
            assert b >= n
            assert b == min(e for e in p.edges if e >= n)

    def test_too_long_rejected(self):
        p = BucketPolicy((16, 32))
        with pytest.raises(ValueError, match="exceeds max bucket"):
            p.bucket_for(33)
        with pytest.raises(ValueError):
            BucketPolicy(())
        with pytest.raises(ValueError):
            BucketPolicy((0, 16))

    def test_assemble_shapes_masks_waste(self):
        p = BucketPolicy((16,))
        reqs = requests_of((8, 12))
        batch, waste = p.assemble(reqs, 16, 4)
        assert batch["seq"].shape == (4, 16)
        assert batch["mask"].shape == (4, 16)
        assert batch["msa"].shape == (4, MSA_DEPTH, 16)
        assert batch["msa_mask"].shape == (4, MSA_DEPTH, 16)
        # masks cover exactly the real tokens, rows 2-3 are batch fill
        assert np.asarray(batch["mask"]).sum(axis=1).tolist() == \
            [8, 12, 0, 0]
        assert np.allclose(waste, 1.0 - (8 + 12) / (4 * 16))
        # padded token slots are zero
        seq = np.asarray(batch["seq"])
        assert (seq[0, 8:] == 0).all() and (seq[2:] == 0).all()

    def test_assemble_pinned_msa_depth(self):
        """Ragged MSA depths under a pinned msa_depth still present ONE
        shape: shallow rows padded+masked, deep ones truncated to the
        first rows (query-first convention)."""
        p = BucketPolicy((16,))
        rng = np.random.default_rng(0)
        shallow = FoldRequest(seq=rng.integers(0, 20, 8),
                              msa=rng.integers(0, 20, (2, 8)))
        deep = FoldRequest(seq=rng.integers(0, 20, 8),
                           msa=rng.integers(0, 20, (6, 8)))
        bare = FoldRequest(seq=rng.integers(0, 20, 8))
        batch, _ = p.assemble([shallow, deep, bare], 16, 4, msa_depth=4)
        assert batch["msa"].shape == (4, 4, 16)
        mm = np.asarray(batch["msa_mask"])
        assert mm[0].sum() == 2 * 8 and mm[1].sum() == 4 * 8
        assert mm[2].sum() == 0                      # msa-free row masked
        # deep MSA keeps its FIRST rows
        assert np.array_equal(np.asarray(batch["msa"])[1, :, :8],
                              deep.msa[:4])
        # msa_depth=0 forces the MSA-free signature even with MSAs
        batch0, _ = p.assemble([shallow, deep], 16, 2, msa_depth=0)
        assert batch0["msa"] is None and batch0["msa_mask"] is None

    def test_assemble_msa_free(self):
        p = BucketPolicy((16,))
        reqs = requests_of((8,), msa_depth=0)
        batch, _ = p.assemble(reqs, 16, 2)
        assert batch["msa"] is None and batch["msa_mask"] is None

    def test_assemble_rejects_overflow(self):
        p = BucketPolicy((16,))
        reqs = requests_of((8, 8, 8))
        with pytest.raises(ValueError, match="> batch_size"):
            p.assemble(reqs, 16, 2)
        with pytest.raises(ValueError, match="> bucket_len"):
            p.assemble(requests_of((24,)), 16, 2)


@pytest.mark.quick
class TestStatsSatellites:
    def test_percentile_interpolates(self):
        vals = list(range(1, 11))  # 1..10
        assert percentile(vals, 50) == pytest.approx(5.5)
        assert percentile(vals, 90) == pytest.approx(9.1)
        assert percentile(vals, 99) == pytest.approx(9.91)
        assert percentile([], 99) == 0.0
        assert percentile([7.0], 90) == 7.0

    def test_steptimer_p90_p99_summary(self):
        t = StepTimer()
        t.durations = [float(i) for i in range(1, 101)]
        assert t.p90 == pytest.approx(percentile(t.durations, 90))
        assert t.p99 == pytest.approx(percentile(t.durations, 99))
        s = t.summary()
        for key in ("count", "mean_s", "p50_s", "p90_s", "p99_s",
                    "best_s"):
            assert key in s
        assert s["p90_s"] <= s["p99_s"]

    def test_metrics_logger_flush_close_context(self, tmp_path):
        path = tmp_path / "m.jsonl"
        with MetricsLogger(str(path), stdout=False) as logger:
            logger.log(step=1, loss=0.5)
            logger.flush()
            rec = json.loads(path.read_text().splitlines()[0])
            assert rec["step"] == 1 and rec["loss"] == 0.5
        assert logger._fh is None          # context exit closed it
        logger.flush()                     # no-op after close, no crash
        logger.close()

    def test_serve_metrics_snapshot(self, tmp_path):
        m = ServeMetrics(str(tmp_path / "s.jsonl"))
        m.record_enqueued(queue_depth=2)
        m.record_served(16, 0.5)
        m.record_batch(bucket_len=16, batch_size=2, n_real=1,
                       real_tokens=8, padding_waste=0.75,
                       batch_latency_s=0.5, queue_depth=1)
        m.record_shed()
        m.record_cache_hit()
        m.record_cache_miss()
        m.record_coalesced()
        snap = m.snapshot()
        assert snap["enqueued"] == 1 and snap["served"] == 1
        assert snap["shed"] == 1 and snap["batches"] == 1
        # cache section always present (zeros when caching is off)
        assert snap["cache"] == {"hits": 1, "misses": 1, "coalesced": 1,
                                 "hit_ratio": 0.5}
        assert snap["padding_waste"] == pytest.approx(1 - 8 / 32)
        assert snap["latency_by_bucket"]["16"]["p99_s"] == \
            pytest.approx(0.5)
        m.close()
        rec = json.loads((tmp_path / "s.jsonl").read_text().splitlines()[0])
        assert "queue_depth" in rec and "p99_latency_s" in rec


class TestExecutor:
    def test_cache_hit_miss_counts(self, model_and_params):
        ex = FoldExecutor(*model_and_params, max_entries=4)
        policy = BucketPolicy((16,))
        batch, _ = policy.assemble(requests_of((8, 12)), 16, 2)
        r1 = ex.run(batch, num_recycles=0)
        assert ex.stats() == dict(ex.stats(), hits=0, misses=1)
        r2 = ex.run(batch, num_recycles=0)
        stats = ex.stats()
        assert stats["hits"] == 1 and stats["misses"] == 1
        assert r1.coords.shape == r2.coords.shape == (2, 16, 3)
        # a different num_recycles is a different executable
        assert ex.key_for(batch, 1) != ex.key_for(batch, 0)

    def test_lru_eviction_bounds_resident_set(self, model_and_params):
        ex = FoldExecutor(*model_and_params, max_entries=1)
        policy = BucketPolicy((16, 32))
        b16, _ = policy.assemble(requests_of((8,)), 16, 1)
        b32, _ = policy.assemble(requests_of((24,)), 32, 1)
        ex.run(b16, 0)
        ex.run(b32, 0)                       # evicts the 16-bucket entry
        stats = ex.stats()
        assert stats["evictions"] == 1 and stats["resident"] == 1
        # ExecKey grew (mesh_shape, model_tag) in ISSUE 7, the variant
        # element in ISSUE 9, and the kernel element in ISSUE 12 (see
        # MIGRATING): single-chip untagged opaque-fold dense executors
        # key as (1,1)/""/"fold"/"dense"
        assert stats["keys"] == [(32, 1, MSA_DEPTH, 0, (1, 1), "",
                                  "fold", "dense")]
        ex.run(b16, 0)                       # cold again after eviction
        assert ex.stats()["misses"] == 3

    def test_warmup_precompiles(self, model_and_params):
        ex = FoldExecutor(*model_and_params, max_entries=4)
        timer = StepTimer()
        fresh = ex.warmup([(16, 1, MSA_DEPTH, 0)], timer=timer)
        assert fresh == 1 and timer.count == 1
        policy = BucketPolicy((16,))
        batch, _ = policy.assemble(requests_of((8,)), 16, 1)
        ex.run(batch, 0)
        stats = ex.stats()
        assert stats["misses"] == 1 and stats["hits"] == 1

    def test_compile_vs_fold_spans(self, model_and_params):
        """Cold key: the trace attributes XLA compile separately from
        the device run; warm key: fold span only."""
        ex = FoldExecutor(*model_and_params, max_entries=4)
        policy = BucketPolicy((16,))
        batch, _ = policy.assemble(requests_of((8,)), 16, 1)
        tracer = obs.Tracer(slow_k=4)
        cold = tracer.start_trace("cold")
        ex.run(batch, 0, trace=cold)
        cold.finish("ok")
        names = [s["name"] for s in cold.record()["spans"]]
        assert names == ["compile", "fold"]
        warm = tracer.start_trace("warm")
        ex.run(batch, 0, trace=warm)
        warm.finish("ok")
        (span,) = warm.record()["spans"]
        assert span["name"] == "fold" and span["dur_s"] > 0


class TestScheduler:
    def test_batch_formation_under_max_wait(self, model_and_params):
        """Two requests < max_batch_size coalesce into ONE batch once the
        oldest has waited max_wait_ms."""
        ex = FoldExecutor(*model_and_params)
        metrics = ServeMetrics()
        config = SchedulerConfig(max_batch_size=4, max_wait_ms=200.0,
                                 num_recycles=0)
        with Scheduler(ex, BucketPolicy((16,)), config, metrics) as sched:
            t1, t2 = [sched.submit(r) for r in requests_of((8, 12))]
            r1, r2 = t1.result(timeout=600), t2.result(timeout=600)
        assert r1.ok and r2.ok
        assert r1.coords.shape == (8, 3) and r2.coords.shape == (12, 3)
        snap = metrics.snapshot()
        assert snap["batches"] == 1        # coalesced, not two singles
        assert snap["served"] == 2

    def test_deadline_shedding(self, model_and_params):
        ex = FoldExecutor(*model_and_params)
        metrics = ServeMetrics()
        config = SchedulerConfig(num_recycles=0)
        with Scheduler(ex, BucketPolicy((16,)), config, metrics) as sched:
            req = requests_of((8,), deadline_s=0.0)[0]
            resp = sched.submit(req).result(timeout=60)
        assert resp.status == "shed" and not resp.ok
        assert resp.coords is None
        assert "deadline" in resp.error
        assert metrics.snapshot()["shed"] == 1
        assert ex.stats()["misses"] == 0   # never touched the executor

    def test_bounded_queue_backpressure(self, model_and_params):
        ex = FoldExecutor(*model_and_params)
        metrics = ServeMetrics()
        # worker can't form a batch (huge max_wait, huge max_batch), so
        # the first request parks in pending and holds queue depth at 1
        config = SchedulerConfig(max_batch_size=8, max_wait_ms=60_000.0,
                                 queue_limit=1, full_policy="reject",
                                 num_recycles=0)
        sched = Scheduler(ex, BucketPolicy((16,)), config, metrics)
        sched.start()
        reqs = requests_of((8, 8))
        ticket = sched.submit(reqs[0])
        with pytest.raises(QueueFullError):
            sched.submit(reqs[1])
        sched.stop(drain=False)
        assert ticket.result(timeout=60).status == "cancelled"
        snap = metrics.snapshot()
        assert snap["rejected"] == 1 and snap["cancelled"] == 1
        assert ex.stats()["misses"] == 0

    def test_metrics_sink_failure_does_not_kill_scheduler(
            self, model_and_params):
        """A failing JSONL sink (disk full) is an observability problem,
        not a serving outage: requests keep resolving ok."""
        class BoomMetrics(ServeMetrics):
            def record_batch(self, *a, **kw):
                raise OSError("disk full")

        ex = FoldExecutor(*model_and_params)
        config = SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                                 num_recycles=0)
        with Scheduler(ex, BucketPolicy((16,)), config,
                       BoomMetrics()) as sched:
            r1 = sched.submit(requests_of((8,))[0]).result(timeout=600)
            r2 = sched.submit(requests_of((12,))[0]).result(timeout=600)
        assert r1.ok and r2.ok

    def test_submit_before_start_rejected(self, model_and_params):
        sched = Scheduler(FoldExecutor(*model_and_params),
                          BucketPolicy((16,)))
        with pytest.raises(RuntimeError, match="before start"):
            sched.submit(requests_of((8,))[0])

    def test_end_to_end_mixed_lengths(self, model_and_params, tmp_path):
        """ISSUE 1 acceptance demo (+ ISSUE 3 obs enabled): >= 32
        concurrent synthetic requests of >= 3 distinct lengths all
        complete with per-request shapes, distinct compilations <=
        buckets used, the JSONL carries queue-depth and p99-latency
        records, and EVERY request yields exactly one complete trace
        whose span tree covers submit -> terminal with a non-zero fold
        span."""
        jsonl = str(tmp_path / "serve.jsonl")
        trace_jsonl = str(tmp_path / "traces.jsonl")
        tracer = obs.Tracer(jsonl_path=trace_jsonl, slow_k=8)
        ex = FoldExecutor(*model_and_params, max_entries=4)
        metrics = ServeMetrics(jsonl)
        config = SchedulerConfig(max_batch_size=4, max_wait_ms=20.0,
                                 num_recycles=0)
        policy = BucketPolicy((16, 32, 48))
        lengths = (12, 24, 40)
        reqs = synthetic_requests(jax.random.PRNGKey(7), num=32,
                                  lengths=lengths, msa_depth=MSA_DEPTH)
        by_id = {r.request_id: r for r in reqs}
        tickets = []
        tickets_lock = threading.Lock()

        with Scheduler(ex, policy, config, metrics,
                       tracer=tracer) as sched:
            def submit_slice(i):
                for r in reqs[i::4]:
                    t = sched.submit(r)
                    with tickets_lock:
                        tickets.append(t)

            threads = [threading.Thread(target=submit_slice, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            responses = [t.result(timeout=600) for t in tickets]

        assert len(responses) == 32
        for resp in responses:
            req = by_id[resp.request_id]
            assert resp.ok, resp.error
            assert resp.coords.shape == (req.length, 3)
            assert resp.confidence.shape == (req.length,)
            assert np.isfinite(resp.coords).all()
            assert resp.bucket_len == policy.bucket_for(req.length)

        stats = ex.stats()
        assert stats["misses"] <= policy.num_buckets    # compile bound
        snap = metrics.snapshot()
        assert snap["served"] == 32 and snap["shed"] == 0
        assert 0.0 < snap["padding_waste"] < 1.0
        metrics.close()

        records = [json.loads(line) for line in open(jsonl)]
        assert records, "no JSONL metrics emitted"
        for rec in records:
            assert "queue_depth" in rec
            assert "p99_latency_s" in rec and rec["p99_latency_s"] > 0

        # ISSUE 3 acceptance: exactly one complete trace per request,
        # span tree covering submit -> terminal with non-zero fold time
        tracer.close()
        traces = [json.loads(line) for line in open(trace_jsonl)]
        trace_by_id = {}
        for tr in traces:
            assert tr["schema"] == 1
            assert tr["request_id"] not in trace_by_id, "duplicate trace"
            trace_by_id[tr["request_id"]] = tr
        assert set(trace_by_id) == set(by_id)
        for tr in traces:
            assert tr["status"] == "ok" and tr["source"] == "fold"
            names = [s["name"] for s in tr["spans"]]
            assert names[0] == "submit" and "queue" in names
            fold_s = sum(s["dur_s"] for s in tr["spans"]
                         if s["name"] in ("fold", "compile"))
            assert fold_s > 0, tr
        assert stats["misses"] <= policy.num_buckets  # tracing minted
        # no extra executables; the slow-trace ring is populated
        assert sched.serve_stats()["traces"]
