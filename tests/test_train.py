"""Training-layer tests: losses (golden + masking), one jitted train step
descends, grad-accum equivalence, checkpoint save/restore round-trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2, constants
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.train import (
    CheckpointManager,
    TrainState,
    adam,
    fit,
    losses,
    make_train_step,
)


def small_model(**kw):
    cfg = dict(dim=32, depth=1, heads=2, dim_head=16)
    cfg.update(kw)
    return Alphafold2(**cfg)


def init_state(model, batch, accum=1):
    params = model.init(
        {"params": jax.random.PRNGKey(0), "mlm": jax.random.PRNGKey(1)},
        batch["seq"], msa=batch["msa"], mask=batch["mask"],
        msa_mask=batch["msa_mask"], train=True)
    return TrainState.create(apply_fn=model.apply, params=params,
                             tx=adam(1e-3, grad_accum_every=accum),
                             rng=jax.random.PRNGKey(2))


class TestLosses:
    def test_ce_ignore_index(self):
        logits = jnp.zeros((2, 4, 5))
        labels = jnp.array([[0, 1, 2, -100], [constants.IGNORE_INDEX] * 4])
        loss = losses.softmax_cross_entropy(logits, labels)
        # uniform logits -> CE = log(5) over the 3 valid positions
        assert np.isclose(float(loss), np.log(5), atol=1e-5)

    def test_ce_perfect_prediction(self):
        labels = jnp.array([[0, 1, 2]])
        logits = jax.nn.one_hot(labels, 4) * 100.0
        assert float(losses.softmax_cross_entropy(logits, labels)) < 1e-3

    def test_distogram_loss_finite(self):
        coords = jnp.cumsum(
            jax.random.normal(jax.random.PRNGKey(0), (1, 12, 3)), axis=1)
        mask = jnp.ones((1, 12), dtype=bool)
        logits = jax.random.normal(jax.random.PRNGKey(1), (1, 12, 12, 37))
        loss = losses.distogram_loss(logits, coords, mask)
        assert np.isfinite(float(loss))

    def test_coords_loss_zero_for_rigid_motion(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 10, 3))
        # rotate 90 deg about z + translate: loss should be ~0 after Kabsch
        rot = jnp.array([[0.0, -1, 0], [1, 0, 0], [0, 0, 1]])
        y = x @ rot + 7.0
        mask = jnp.ones((1, 10), dtype=bool)
        assert float(losses.coords_loss(y, x, mask)) < 1e-4

    def test_lddt_confidence_loss(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (1, 10, 3)) * 4
        conf = jnp.zeros((1, 10, 1))
        mask = jnp.ones((1, 10), dtype=bool)
        loss = losses.lddt_confidence_loss(conf, x, x, mask)
        # sigmoid(0)=0.5 vs perfect lddt 1.0 -> mse 0.25
        assert np.isclose(float(loss), 0.25, atol=1e-5)


class TestTrainStep:
    def test_distogram_step_descends(self):
        model = small_model()
        batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=16,
                                msa_depth=3)
        state = init_state(model, batch)
        step = jax.jit(make_train_step(model))
        state, m0 = step(state, batch)
        loss0 = float(m0["loss"])
        for _ in range(8):
            state, metrics = step(state, batch)
        assert float(metrics["loss"]) < loss0
        assert int(state.step) == 9

    def test_coords_step(self):
        model = small_model(predict_coords=True, structure_module_depth=1)
        batch = synthetic_batch(jax.random.PRNGKey(1), batch=1, seq_len=12,
                                msa_depth=3)
        state = init_state(model, batch)
        step = jax.jit(make_train_step(model))
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert "coords_loss" in metrics

    def test_recycled_train_step(self):
        """make_recycled_train_step: sampled-recycle training runs as
        one compiled program, loss finite and descending over repeats,
        and the sampled counts actually vary across steps."""
        from alphafold2_tpu.train import make_recycled_train_step

        model = small_model(predict_coords=True, structure_module_depth=1)
        batch = synthetic_batch(jax.random.PRNGKey(2), batch=1, seq_len=12,
                                msa_depth=3, with_coords=True)
        state = init_state(model, batch)
        step = jax.jit(make_recycled_train_step(model, max_recycles=2))
        seen = set()
        state, m0 = step(state, batch)
        loss0 = float(m0["loss"])
        for _ in range(10):
            state, metrics = step(state, batch)
            assert np.isfinite(float(metrics["loss"]))
            seen.add(int(metrics["recycles"]))
        assert float(metrics["loss"]) < loss0
        assert len(seen) > 1, f"recycle counts never varied: {seen}"

    def test_coords_model_without_coords_target(self):
        # a coords model trained on a batch with no coords target must
        # still get a ReturnValues (not bare coords) so the distogram/MLM
        # terms remain trainable (regression: ADVICE.md round 1)
        from alphafold2_tpu.train.loop import compute_loss

        model = small_model(predict_coords=True, structure_module_depth=1)
        batch = synthetic_batch(jax.random.PRNGKey(4), batch=1, seq_len=12,
                                msa_depth=3, with_coords=False)
        state = init_state(model, batch)
        loss, metrics = compute_loss(model, state.params, batch,
                                     jax.random.PRNGKey(7), train=True)
        assert np.isfinite(float(loss))
        assert "coords_loss" not in metrics
        step = jax.jit(make_train_step(model))
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_grad_accum_matches_big_batch_direction(self):
        # with MultiSteps(k), params change only every k micro-steps
        model = small_model()
        batch = synthetic_batch(jax.random.PRNGKey(2), batch=1, seq_len=12,
                                msa_depth=3)
        state = init_state(model, batch, accum=4)
        step = jax.jit(make_train_step(model))
        p0 = state.params
        for i in range(3):
            state, _ = step(state, batch)
        # not yet applied
        diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                             p0["params"], state.params["params"])
        assert max(jax.tree.leaves(diffs)) == 0.0
        state, _ = step(state, batch)
        diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                             p0["params"], state.params["params"])
        assert max(jax.tree.leaves(diffs)) > 0.0


class TestDropout:
    """Nonzero dropout through the full jit+scan+remat train path
    (VERDICT round-1 Weak #8: configured but never exercised)."""

    def test_dropout_train_step_descends(self):
        model = small_model(depth=2, attn_dropout=0.1, ff_dropout=0.1)
        batch = synthetic_batch(jax.random.PRNGKey(6), batch=1, seq_len=12,
                                msa_depth=3)
        state = init_state(model, batch)
        step = jax.jit(make_train_step(model))
        state, m0 = step(state, batch)
        assert np.isfinite(float(m0["loss"]))
        for _ in range(4):
            state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert int(state.step) == 5

    def test_dropout_stochastic_train_deterministic_eval(self):
        from alphafold2_tpu.train.loop import compute_loss

        model = small_model(depth=2, attn_dropout=0.3, ff_dropout=0.3)
        batch = synthetic_batch(jax.random.PRNGKey(7), batch=1, seq_len=12,
                                msa_depth=3)
        state = init_state(model, batch)
        # at init the attention/FF output projections are ZERO (blocks
        # start as identity on the residual stream), which makes every
        # dropout mask invisible; perturb params off init so dropout has
        # something to bite on
        rng = np.random.default_rng(0)
        params = jax.tree.map(
            lambda a: a + jnp.asarray(
                0.02 * rng.standard_normal(a.shape), a.dtype),
            state.params)

        # isolate the dropout stream: same mlm key, different dropout keys
        def trunk_out(dropout_key):
            ret = model.apply(
                params, batch["seq"], msa=batch["msa"],
                mask=batch["mask"], msa_mask=batch["msa_mask"],
                train=True, return_trunk=True,
                rngs={"mlm": jax.random.PRNGKey(0),
                      "dropout": dropout_key})
            return np.asarray(ret.distance, dtype=np.float32)

        d1 = trunk_out(jax.random.PRNGKey(10))
        d2 = trunk_out(jax.random.PRNGKey(11))
        d1b = trunk_out(jax.random.PRNGKey(10))
        # different dropout keys must change the output — proves the
        # 'dropout' rng stream reaches the layers under scan+remat —
        # while the same key reproduces exactly (determinism)
        assert not np.allclose(d1, d2)
        np.testing.assert_array_equal(d1, d1b)
        e1, _ = compute_loss(model, state.params, batch,
                             jax.random.PRNGKey(10), train=False)
        e2, _ = compute_loss(model, state.params, batch,
                             jax.random.PRNGKey(11), train=False)
        assert np.isclose(float(e1), float(e2))


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        model = small_model()
        batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=12,
                                msa_depth=3)
        state = init_state(model, batch)
        step = jax.jit(make_train_step(model))
        state, _ = step(state, batch)

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        saved_step = mgr.save(state)
        assert mgr.latest_step() == saved_step

        fresh = init_state(model, batch)
        restored = mgr.restore(fresh)
        for a, b in zip(jax.tree.leaves(restored.params),
                        jax.tree.leaves(state.params)):
            assert np.allclose(a, b)
        assert int(restored.step) == int(state.step)

        # restored state trains on
        restored, metrics = step(restored, batch)
        assert np.isfinite(float(metrics["loss"]))


class TestGuard:
    def test_guarded_step_skips_nonfinite(self):
        from alphafold2_tpu.train.guard import all_finite, guarded_train_step

        # toy model: loss = sum(w * x); a NaN batch poisons loss + grads
        tx = adam(1e-2)
        params = {"w": jnp.ones((4,))}
        state = TrainState.create(
            apply_fn=lambda *a: None, params=params, tx=tx,
            rng=jax.random.PRNGKey(0))

        def raw_step(state, batch):
            new_rng = jax.random.split(state.rng)[1]

            def loss_fn(p):
                loss = (p["w"] * batch).sum()
                return loss, {"loss": loss}

            grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
            return (state.apply_gradients(grads=grads).replace(rng=new_rng),
                    metrics)

        step = jax.jit(guarded_train_step(raw_step))

        state1, metrics = step(state, jnp.ones((4,)))
        assert float(metrics["skipped"]) == 0.0
        assert not np.allclose(np.asarray(state1.params["w"]),
                               np.asarray(params["w"]))

        state2, metrics2 = step(state1, jnp.full((4,), jnp.nan))
        assert float(metrics2["skipped"]) == 1.0
        assert np.array_equal(np.asarray(state2.params["w"]),
                              np.asarray(state1.params["w"]))
        # optimizer state must also be reverted, not just params
        assert bool(all_finite(state2.opt_state))
        # step/rng still advance so the schedule moves on
        assert int(state2.step) == int(state1.step) + 1
        assert not np.array_equal(np.asarray(state2.rng),
                                  np.asarray(state1.rng))

        # recovery: the next clean step trains on without contamination
        state3, metrics3 = step(state2, jnp.ones((4,)))
        assert float(metrics3["skipped"]) == 0.0
        assert bool(all_finite(state3.params))

    def test_guard_rejects_poisoned_accumulator(self):
        # with MultiSteps accumulation, a micro-step can have a FINITE
        # loss and FINITE params (no apply yet) while the gradient is
        # non-finite — poisoning only the accumulator. The guard must gate
        # on opt_state finiteness or training wedges permanently
        # (regression: ADVICE.md round 1)
        from alphafold2_tpu.train.guard import all_finite, guarded_train_step

        params = {"w": jnp.ones((4,))}
        state = TrainState.create(
            apply_fn=lambda *a: None, params=params,
            tx=adam(1e-2, grad_accum_every=2), rng=jax.random.PRNGKey(0))

        def raw_step(state, batch):
            new_rng = jax.random.split(state.rng)[1]

            def loss_fn(p):
                # sqrt at 0: value 0 (finite), gradient inf (poison)
                loss = jnp.sqrt((p["w"] * batch).sum())
                return loss, {"loss": loss}

            grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
            return (state.apply_gradients(grads=grads).replace(rng=new_rng),
                    metrics)

        step = jax.jit(guarded_train_step(raw_step))

        # poison micro-step: loss finite, params untouched, grads inf
        state1, metrics1 = step(state, jnp.zeros((4,)))
        assert np.isfinite(float(metrics1["loss"]))
        assert bool(all_finite(state1.params))
        assert float(metrics1["skipped"]) == 1.0
        # the accumulator was rolled back, not kept poisoned
        assert bool(all_finite(state1.opt_state))

        # training continues cleanly through a full accumulation window
        state2, m2 = step(state1, jnp.ones((4,)))
        state3, m3 = step(state2, jnp.ones((4,)))
        assert float(m2["skipped"]) == 0.0 and float(m3["skipped"]) == 0.0
        assert bool(all_finite(state3.params))
        assert bool(all_finite(state3.opt_state))

    def test_autocheckpointer(self, tmp_path):
        from alphafold2_tpu.train.guard import AutoCheckpointer

        model = small_model()
        batch = synthetic_batch(jax.random.PRNGKey(5), batch=1, seq_len=12,
                                msa_depth=3)
        state = init_state(model, batch)
        ck = AutoCheckpointer(str(tmp_path / "auto"), every=2)

        # no checkpoint yet: resume_or falls back to the given state
        fallback = ck.resume_or(state)
        assert fallback is state

        # off-cadence steps are skipped
        ck.maybe_save(state.replace(step=jnp.asarray(1)))
        assert ck.manager.latest_step() is None
        ck.maybe_save(state.replace(step=jnp.asarray(0)))
        assert ck.manager.latest_step() is None

        # on-cadence save + resume
        state = state.replace(step=jnp.asarray(2))
        ck.maybe_save(state)
        assert ck.manager.latest_step() == 2
        resumed = ck.resume_or(init_state(model, batch))
        assert int(resumed.step) == 2

        # failure-path save overwrites/creates at the current step
        ck.on_failure(state.replace(step=jnp.asarray(3)))
        assert ck.manager.latest_step() == 3


class TestSchedule:
    def test_warmup_cosine_descends_and_warms(self):
        """Warmup: first update tiny; peak: updates grow; beyond the
        reference's bare Adam (train_pre.py:16) but default-off."""
        import optax

        tx = adam(1e-2, warmup_steps=5, decay_steps=50)
        params = {"w": jnp.ones((4,))}
        opt_state = tx.init(params)
        grads = {"w": jnp.ones((4,))}
        sizes = []
        for _ in range(6):
            updates, opt_state = tx.update(grads, opt_state, params)
            sizes.append(float(jnp.abs(updates["w"]).max()))
        # step 0 uses lr ~0 (warmup from 0); later steps approach peak
        assert sizes[0] < 1e-4
        assert sizes[-1] > sizes[0]

    @pytest.mark.quick
    def test_warmup_only_holds_peak(self):
        """warmup_steps without decay_steps must HOLD peak LR after the
        ramp — the naive warmup_cosine spelling silently decayed 10x one
        step after warmup (round-2 ADVICE, medium)."""
        tx = adam(1e-2, warmup_steps=5, decay_steps=None)
        params = {"w": jnp.ones((4,))}
        opt_state = tx.init(params)
        grads = {"w": jnp.ones((4,))}
        sizes = []
        for _ in range(60):
            updates, opt_state = tx.update(grads, opt_state, params)
            sizes.append(float(jnp.abs(updates["w"]).max()))
        # post-warmup updates stay peak-sized for the rest of training
        assert sizes[-1] > 0.5 * max(sizes), (sizes[-1], max(sizes))
        assert sizes[0] < 1e-4  # and warmup still ramps from ~0

    @pytest.mark.quick
    def test_default_matches_reference_constant_lr(self):
        tx_plain = adam(1e-3)
        tx_sched = adam(1e-3, warmup_steps=0, decay_steps=None)
        params = {"w": jnp.ones((3,))}
        g = {"w": jnp.full((3,), 0.5)}
        s1, s2 = tx_plain.init(params), tx_sched.init(params)
        u1, _ = tx_plain.update(g, s1, params)
        u2, _ = tx_sched.update(g, s2, params)
        assert np.allclose(np.asarray(u1["w"]), np.asarray(u2["w"]))

    def test_config_roundtrip_with_schedule(self):
        from alphafold2_tpu.config import Experiment

        exp = Experiment()
        exp.train.warmup_steps = 100
        exp.train.decay_steps = 1000
        back = Experiment.from_json(exp.to_json())
        assert back.train.warmup_steps == 100
        model, tx, mesh = back.build()
        assert tx is not None


class TestPrefetch:
    """Async host->device staging (train/prefetch.py) — the torch
    DataLoader-workers analog (reference trrosetta.py:451-476)."""

    @pytest.mark.quick
    def test_order_and_values_preserved(self):
        from alphafold2_tpu.train import device_prefetch

        src = [{"x": np.full((4, 2), i, np.float32)} for i in range(7)]
        out = list(device_prefetch(iter(src), size=3))
        assert len(out) == 7
        for i, b in enumerate(out):
            assert float(np.asarray(b["x"])[0, 0]) == i

    @pytest.mark.quick
    def test_exception_propagates(self):
        from alphafold2_tpu.train import device_prefetch

        def bad():
            yield {"x": np.zeros((2,), np.float32)}
            raise RuntimeError("loader died")

        it = device_prefetch(bad(), size=2)
        next(it)
        with pytest.raises(RuntimeError, match="loader died"):
            next(it)

    @pytest.mark.quick
    def test_worker_stops_on_close(self):
        """Closing the consumer stops the worker: a shared finite
        iterator loses at most size+1 lookahead batches, and no thread
        is left blocked forever."""
        import threading
        import time

        from alphafold2_tpu.train import device_prefetch

        consumed = []

        def src():
            for i in range(100):
                consumed.append(i)
                yield {"x": np.full((2,), i, np.float32)}

        it = device_prefetch(src(), size=2)
        next(it), next(it)
        it.close()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and any(
                t.name == "device-prefetch" and t.is_alive()
                for t in threading.enumerate()):
            time.sleep(0.05)
        assert not any(t.name == "device-prefetch" and t.is_alive()
                       for t in threading.enumerate())
        # yielded 2 + queue capacity 2 + at most 1 in flight
        assert len(consumed) <= 5, consumed

    @pytest.mark.quick
    def test_single_device_batches_are_committed(self):
        """No mesh: batches still come back as committed device arrays
        (the H2D transfer happened in the worker, not in the step)."""
        from alphafold2_tpu.train import device_prefetch

        src = [{"x": np.ones((2, 3), np.float32)}]
        out = next(device_prefetch(iter(src), size=1))
        # already a device array (transfer happened in the worker);
        # device_put without an explicit device leaves it uncommitted,
        # which is what the jitted step wants (free to keep placement)
        assert isinstance(out["x"], jax.Array)

    def test_mesh_placement_from_calling_thread(self):
        """active_mesh() is thread-local; the prefetch worker must still
        place batches with the caller's mesh."""
        from alphafold2_tpu.parallel import make_mesh, use_mesh
        from alphafold2_tpu.train import device_prefetch, shard_batch

        mesh = make_mesh(2, 2, 2)
        src = [{"x": np.arange(8, dtype=np.float32).reshape(2, 4)}]
        with use_mesh(mesh):
            out = next(device_prefetch(iter(src), size=1))
            want = shard_batch(src[0], mesh)
        assert out["x"].sharding == want["x"].sharding
        assert np.allclose(np.asarray(out["x"]), src[0]["x"])

    def test_fit_with_prefetch_trains(self):
        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16)
        batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=8,
                                msa_depth=2, with_coords=True)
        params = model.init(
            {"params": jax.random.PRNGKey(1), "mlm": jax.random.PRNGKey(2)},
            batch["seq"], msa=batch["msa"], mask=batch["mask"],
            msa_mask=batch["msa_mask"], train=True)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=adam(1e-3), rng=jax.random.PRNGKey(3))

        def stream():
            i = 0
            while True:
                yield synthetic_batch(jax.random.PRNGKey(i), batch=1,
                                      seq_len=8, msa_depth=2,
                                      with_coords=True)
                i += 1

        state, history = fit(model, state, stream(), num_steps=4,
                             log_every=1, prefetch=2)
        assert int(state.step) == 4
        assert all(np.isfinite(h["loss"]) for h in history)


class TestShardedCheckpoint:
    def test_restore_preserves_mesh_sharding(self, tmp_path):
        """Save a ZeRO/TP-sharded state, restore into a sharded target:
        leaves come back with their NamedShardings and equal values."""
        from alphafold2_tpu.parallel import (make_mesh,
                                             shard_pytree_tp_zero, use_mesh)
        from alphafold2_tpu.train import CheckpointManager

        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16)
        batch = synthetic_batch(jax.random.PRNGKey(0), batch=2, seq_len=8,
                                msa_depth=2, with_coords=True)
        mesh = make_mesh(2, 2, 2)

        def build():
            params = model.init(
                {"params": jax.random.PRNGKey(1),
                 "mlm": jax.random.PRNGKey(2)},
                batch["seq"], msa=batch["msa"], mask=batch["mask"],
                msa_mask=batch["msa_mask"], train=True)
            return TrainState.create(apply_fn=model.apply, params=params,
                                     tx=adam(1e-3),
                                     rng=jax.random.PRNGKey(3))

        with use_mesh(mesh):
            state = shard_pytree_tp_zero(build(), mesh)
            ck = CheckpointManager(str(tmp_path / "ck"))
            ck.save(state, step=0)

            target = shard_pytree_tp_zero(build(), mesh)
            restored = ck.restore(target, step=0)

        for a, b in zip(jax.tree.leaves(restored.params),
                        jax.tree.leaves(state.params)):
            assert a.sharding == b.sharding, (a.sharding, b.sharding)
            assert np.allclose(np.asarray(a), np.asarray(b))
