"""Model-level tests mirroring the reference's tests/test_attention.py
coverage (basic trunk, no-MSA, anglegrams, templates, extra-MSA, embedds,
coords, backward, confidence, recycling) plus invariance/property tests the
reference lacks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2, constants
from alphafold2_tpu.model import Evoformer, ReturnValues
from alphafold2_tpu.model.mlm import MLM, get_mask_subset_with_prob


def make_inputs(b=2, n=16, m=5, key=0):
    k = jax.random.PRNGKey(key)
    k1, k2 = jax.random.split(k)
    return dict(
        seq=jax.random.randint(k1, (b, n), 0, 21),
        msa=jax.random.randint(k2, (b, m, n), 0, 21),
        mask=jnp.ones((b, n), dtype=bool),
        msa_mask=jnp.ones((b, m, n), dtype=bool),
    )


def small_model(**kwargs):
    defaults = dict(dim=32, depth=1, heads=2, dim_head=16)
    defaults.update(kwargs)
    return Alphafold2(**defaults)


class TestTrunk:
    def test_main(self):
        # reference test_attention.py::test_main
        model = small_model(depth=2)
        inp = make_inputs()
        params = model.init(jax.random.PRNGKey(1), **inp)
        ret = model.apply(params, **inp)
        assert isinstance(ret, ReturnValues)
        assert ret.distance.shape == (2, 16, 16, constants.DISTOGRAM_BUCKETS)
        assert bool(jnp.isfinite(ret.distance).all())

    def test_no_msa(self):
        # reference test_attention.py::test_no_msa
        model = small_model()
        inp = make_inputs()
        del inp["msa"], inp["msa_mask"]
        params = model.init(jax.random.PRNGKey(1), **inp)
        ret = model.apply(params, **inp)
        assert ret.distance.shape == (2, 16, 16, constants.DISTOGRAM_BUCKETS)

    def test_anglegrams(self):
        # reference test_attention.py::test_anglegrams
        model = small_model(predict_angles=True)
        inp = make_inputs()
        params = model.init(jax.random.PRNGKey(1), **inp)
        ret = model.apply(params, **inp)
        assert ret.theta.shape == (2, 16, 16, constants.THETA_BUCKETS)
        assert ret.phi.shape == (2, 16, 16, constants.PHI_BUCKETS)
        assert ret.omega.shape == (2, 16, 16, constants.OMEGA_BUCKETS)

    def test_symmetrized_omega(self):
        model = small_model(predict_angles=True, symmetrize_omega=True)
        inp = make_inputs()
        params = model.init(jax.random.PRNGKey(1), **inp)
        ret = model.apply(params, **inp)
        om = ret.omega
        assert np.allclose(om, om.swapaxes(1, 2), atol=1e-4)

    def test_distogram_symmetry(self):
        # the distogram head consumes the symmetrized pair rep
        model = small_model()
        inp = make_inputs()
        params = model.init(jax.random.PRNGKey(1), **inp)
        ret = model.apply(params, **inp)
        assert np.allclose(ret.distance, ret.distance.swapaxes(1, 2),
                           atol=1e-4)

    def test_templates(self):
        # reference test_attention.py::test_templates
        model = small_model(templates_dim=8)
        inp = make_inputs(b=1, n=8, m=3)
        templates = dict(
            templates_feats=jax.random.normal(
                jax.random.PRNGKey(3), (1, 2, 8, 8, 8)),
            templates_mask=jnp.ones((1, 2, 8), dtype=bool),
            templates_angles=jax.random.normal(
                jax.random.PRNGKey(4), (1, 2, 8, 55)),
        )
        params = model.init(jax.random.PRNGKey(1), **inp, **templates)
        ret = model.apply(params, **inp, **templates)
        assert ret.distance.shape == (1, 8, 8, constants.DISTOGRAM_BUCKETS)

    def test_extra_msa(self):
        # reference test_attention.py::test_extra_msa
        model = small_model(predict_coords=True, structure_module_depth=1)
        inp = make_inputs(b=1, n=8, m=3)
        extra = dict(
            extra_msa=jax.random.randint(jax.random.PRNGKey(5), (1, 4, 8),
                                         0, 21),
            extra_msa_mask=jnp.ones((1, 4, 8), dtype=bool),
        )
        params = model.init(jax.random.PRNGKey(1), **inp, **extra)
        coords = model.apply(params, **inp, **extra)
        assert coords.shape == (1, 8, 3)

    def test_embedds(self):
        # reference test_attention.py::test_embedless_model
        model = small_model(num_embedds=64)
        inp = make_inputs(b=1, n=8)
        del inp["msa"], inp["msa_mask"]
        embedds = jax.random.normal(jax.random.PRNGKey(6), (1, 1, 8, 64))
        params = model.init(jax.random.PRNGKey(1), **inp, embedds=embedds)
        ret = model.apply(params, **inp, embedds=embedds)
        assert ret.distance.shape == (1, 8, 8, constants.DISTOGRAM_BUCKETS)

    def test_one_params_tree_serves_all_configs(self):
        # init with the plain path, then apply every optional branch with the
        # same tree (init-time coverage contract)
        model = small_model(predict_coords=True, structure_module_depth=1,
                            templates_dim=8, num_embedds=64)
        inp = make_inputs(b=1, n=8, m=3)
        params = model.init(jax.random.PRNGKey(1), **inp)
        # trunk-only view of a coords model
        ret = model.apply(params, **inp, return_trunk=True)
        assert ret.distance is not None
        # templates on
        model.apply(
            params, **inp,
            templates_feats=jnp.zeros((1, 2, 8, 8, 8)),
            templates_mask=jnp.ones((1, 2, 8), dtype=bool),
            templates_angles=jnp.zeros((1, 2, 8, 55)))
        # extra MSA on
        model.apply(params, **inp,
                    extra_msa=jnp.zeros((1, 4, 8), dtype=jnp.int32),
                    extra_msa_mask=jnp.ones((1, 4, 8), dtype=bool))
        # embedds path
        model.apply(params, seq=inp["seq"], mask=inp["mask"],
                    embedds=jnp.zeros((1, 1, 8, 64)))
        # train path
        model.apply(params, **inp, train=True,
                    rngs={"mlm": jax.random.PRNGKey(2)})


class TestCoords:
    def test_coords_shape(self):
        # reference test_attention.py::test_coords (asserts (2,16,3))
        model = small_model(predict_coords=True, structure_module_depth=2)
        inp = make_inputs()
        params = model.init(jax.random.PRNGKey(1), **inp)
        coords = model.apply(params, **inp)
        assert coords.shape == (2, 16, 3)
        assert bool(jnp.isfinite(coords).all())

    def test_coords_backward(self):
        # reference test_attention.py::test_coords_backwards
        model = small_model(predict_coords=True, structure_module_depth=2)
        inp = make_inputs(b=1, n=8)
        params = model.init(jax.random.PRNGKey(1), **inp)

        def loss_fn(p):
            coords = model.apply(p, **inp)
            return jnp.sum(coords ** 2)

        grads = jax.grad(loss_fn)(params)
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(bool(jnp.isfinite(g).all()) for g in leaves)
        # gradient must reach the trunk
        total = sum(float(jnp.abs(g).sum()) for g in leaves)
        assert total > 0

    def test_confidence(self):
        # reference test_attention.py::test_confidence
        model = small_model(predict_coords=True, structure_module_depth=1)
        inp = make_inputs()
        params = model.init(jax.random.PRNGKey(1), **inp)
        coords, confidence = model.apply(params, **inp,
                                         return_confidence=True)
        assert coords.shape == (2, 16, 3)
        assert confidence.shape == (2, 16, 1)

    def test_recycling(self):
        # reference test_attention.py::test_recycling
        model = small_model(predict_coords=True, structure_module_depth=1)
        inp = make_inputs(b=1, n=8)
        params = model.init(jax.random.PRNGKey(1), **inp)
        coords, ret = model.apply(params, **inp, return_aux_logits=True,
                                  return_recyclables=True)
        assert ret.recyclables is not None
        coords2, ret2 = model.apply(params, **inp,
                                    recyclables=ret.recyclables,
                                    return_aux_logits=True,
                                    return_recyclables=True)
        assert coords2.shape == coords.shape
        assert bool(jnp.isfinite(coords2).all())


class TestMLM:
    def test_mask_subset_prob(self):
        rng = jax.random.PRNGKey(0)
        mask = jnp.ones((4, 100), dtype=bool)
        subset = get_mask_subset_with_prob(rng, mask, 0.15)
        assert subset.shape == (4, 100)
        counts = subset.sum(-1)
        assert ((counts > 5) & (counts <= 15)).all()
        # subset respects the validity mask
        mask2 = mask.at[:, 50:].set(False)
        subset2 = get_mask_subset_with_prob(rng, mask2, 0.15)
        assert not bool(subset2[:, 50:].any())

    def test_noise_and_loss(self):
        mlm = MLM(dim=16, num_tokens=21, mask_id=21)
        seq = jax.random.randint(jax.random.PRNGKey(1), (2, 4, 50), 1, 21)
        mask = jnp.ones_like(seq, dtype=bool)
        noised, replaced = mlm.noise(jax.random.PRNGKey(2), seq, mask)
        assert noised.shape == seq.shape
        assert bool(replaced.any())
        # unreplaced positions untouched
        assert bool((jnp.where(replaced, True, noised == seq)).all())
        params = mlm.init(jax.random.PRNGKey(3),
                          jnp.zeros((2, 4, 50, 16)), seq, replaced)
        loss = mlm.apply(params, jnp.zeros((2, 4, 50, 16)), seq, replaced)
        assert np.isfinite(float(loss))

    def test_mlm_loss_in_training_forward(self):
        model = small_model()
        inp = make_inputs(b=1, n=8)
        params = model.init(
            {"params": jax.random.PRNGKey(1), "mlm": jax.random.PRNGKey(2)},
            **inp, train=True)
        ret = model.apply(params, **inp, train=True,
                          rngs={"mlm": jax.random.PRNGKey(3)})
        assert ret.msa_mlm_loss is not None
        # ~ uniform CE over 21 classes at random init
        assert 1.0 < float(ret.msa_mlm_loss) < 6.0


class TestEvoformerModule:
    def test_standalone_evoformer(self):
        # public Evoformer export (reference __init__.py:1)
        ev = Evoformer(dim=16, depth=2, heads=2, dim_head=8)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 8, 16))
        m = jax.random.normal(jax.random.PRNGKey(1), (1, 3, 8, 16))
        params = ev.init(jax.random.PRNGKey(2), x, m)
        x2, m2 = ev.apply(params, x, m)
        assert x2.shape == x.shape and m2.shape == m.shape

    def test_scan_matches_loop(self):
        # scanned stack must equal the unrolled loop given identical params
        ev_scan = Evoformer(dim=16, depth=3, heads=2, dim_head=8,
                            use_scan=True)
        ev_loop = Evoformer(dim=16, depth=3, heads=2, dim_head=8,
                            use_scan=False)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 6, 16))
        m = jax.random.normal(jax.random.PRNGKey(1), (1, 3, 6, 16))
        p_scan = ev_scan.init(jax.random.PRNGKey(2), x, m)

        # re-key loop params from the scanned (stacked) params
        stacked = p_scan["params"]["layers"]["block"]
        p_loop = {"params": {}}
        for i in range(3):
            p_loop["params"][f"layers_{i}"] = jax.tree.map(
                lambda t, i=i: t[i], stacked)
        xs, ms = ev_scan.apply(p_scan, x, m)
        xl, ml = ev_loop.apply(p_loop, x, m)
        assert np.allclose(xs, xl, atol=1e-5)
        assert np.allclose(ms, ml, atol=1e-5)


class TestMasking:
    def test_padding_invariance(self):
        """Padded positions must not change unpadded outputs."""
        model = small_model()
        n_real, n_pad = 8, 12
        k = jax.random.PRNGKey(7)
        seq_real = jax.random.randint(k, (1, n_real), 1, 21)
        msa_real = jax.random.randint(k, (1, 3, n_real), 1, 21)

        seq_padded = jnp.pad(seq_real, ((0, 0), (0, n_pad - n_real)))
        msa_padded = jnp.pad(msa_real, ((0, 0), (0, 0), (0, n_pad - n_real)))
        mask = jnp.arange(n_pad)[None, :] < n_real
        msa_mask = jnp.broadcast_to(mask[:, None, :], (1, 3, n_pad))

        params = model.init(jax.random.PRNGKey(1), seq_padded,
                            msa=msa_padded, mask=mask, msa_mask=msa_mask)
        ret_pad = model.apply(params, seq_padded, msa=msa_padded, mask=mask,
                              msa_mask=msa_mask)
        ret_real = model.apply(
            params, seq_real, msa=msa_real,
            mask=jnp.ones((1, n_real), dtype=bool),
            msa_mask=jnp.ones((1, 3, n_real), dtype=bool))
        assert np.allclose(ret_pad.distance[:, :n_real, :n_real],
                           ret_real.distance, atol=2e-3)


class TestPredict:
    def test_fold_with_recycling(self):
        from alphafold2_tpu.predict import fold

        model = small_model(predict_coords=True, structure_module_depth=1)
        inp = make_inputs(b=1, n=8, m=3)
        params = model.init(jax.random.PRNGKey(1), **inp)
        result = fold(model, params, inp["seq"], msa=inp["msa"],
                      mask=inp["mask"], msa_mask=inp["msa_mask"],
                      num_recycles=2)
        assert result.coords.shape == (1, 8, 3)
        assert result.confidence.shape == (1, 8)
        assert ((result.confidence >= 0) & (result.confidence <= 1)).all()
        assert result.distogram.shape == (1, 8, 8, 37)
        assert bool(jnp.isfinite(result.coords).all())

    def test_fold_zero_recycles(self):
        from alphafold2_tpu.predict import fold

        model = small_model(predict_coords=True, structure_module_depth=1)
        inp = make_inputs(b=1, n=8, m=3)
        params = model.init(jax.random.PRNGKey(1), **inp)
        result = fold(model, params, inp["seq"], msa=inp["msa"],
                      mask=inp["mask"], msa_mask=inp["msa_mask"],
                      num_recycles=0)
        assert result.coords.shape == (1, 8, 3)

    def test_fold_under_jit(self):
        from alphafold2_tpu.predict import fold

        model = small_model(predict_coords=True, structure_module_depth=1)
        inp = make_inputs(b=1, n=8, m=3)
        params = model.init(jax.random.PRNGKey(1), **inp)
        jfold = jax.jit(lambda p: fold(model, p, inp["seq"], msa=inp["msa"],
                                       mask=inp["mask"],
                                       msa_mask=inp["msa_mask"],
                                       num_recycles=1))
        result = jfold(params)
        assert result.coords.shape == (1, 8, 3)

    def test_fold_and_write(self, tmp_path):
        from alphafold2_tpu.predict import fold_and_write

        model = small_model(predict_coords=True, structure_module_depth=1)
        inp = make_inputs(b=1, n=8, m=3)
        params = model.init(jax.random.PRNGKey(1), **inp)
        paths = fold_and_write(model, params, inp["seq"],
                               out_path=str(tmp_path / "pred.pdb"),
                               msa=inp["msa"], mask=inp["mask"],
                               msa_mask=inp["msa_mask"], num_recycles=1)
        assert paths == [str(tmp_path / "pred.pdb")]
        text = open(paths[0]).read()
        assert text.startswith("ATOM")

    def test_fold_and_write_batched(self, tmp_path):
        from alphafold2_tpu.predict import fold_and_write

        model = small_model(predict_coords=True, structure_module_depth=1)
        inp = make_inputs(b=2, n=8, m=3)
        params = model.init(jax.random.PRNGKey(1), **inp)
        paths = fold_and_write(model, params, inp["seq"],
                               out_path=str(tmp_path / "pred.pdb"),
                               msa=inp["msa"], mask=inp["mask"],
                               msa_mask=inp["msa_mask"], num_recycles=0)
        assert paths == [str(tmp_path / "pred_0.pdb"),
                         str(tmp_path / "pred_1.pdb")]
        for path in paths:
            assert open(path).read().startswith("ATOM")


class TestEvaluateScript:
    def test_fold_and_score_on_crystal_fixture(self, tmp_path):
        """scripts/evaluate.py: the inference + eval-metrics stack
        (SURVEY §3.5) end to end on the 1H22 fixture — folds, scores
        vs the crystal CA trace, writes PDB + metrics JSON."""
        import json
        import os

        from scripts.evaluate import main

        fixture = os.path.join(os.path.dirname(__file__), "data",
                               "1h22_head.pdb")
        out_pdb = str(tmp_path / "pred.pdb")
        out_json = str(tmp_path / "metrics.json")
        metrics = main(["--pdb", fixture, "--recycles", "1",
                        "--out", out_pdb, "--json", out_json])
        assert metrics["n_residues"] == 72
        for k in ("kabsch_rmsd", "tm_score", "gdt_ts", "lddt"):
            assert np.isfinite(metrics[k]), (k, metrics)
        assert 0.0 <= metrics["tm_score"] <= 1.0
        assert 0.0 <= metrics["lddt"] <= 1.0
        assert 0.0 <= metrics["mean_confidence"] <= 1.0
        assert os.path.exists(out_pdb)
        with open(out_json) as f:
            assert json.load(f)["n_residues"] == 72

    def test_evaluate_restores_training_checkpoint(self, tmp_path):
        """train_distogram writes an orbax checkpoint (MultiSteps-wrapped
        optimizer); evaluate --checkpoint must restore it — the tx pytree
        layouts have to match across the two scripts."""
        import json
        import os

        from scripts.evaluate import main as eval_main
        from scripts.train_distogram import main as train_main

        fixture = os.path.join(os.path.dirname(__file__), "data",
                               "1h22_head.pdb")
        cfg = {"model": {"dim": 32, "depth": 1, "heads": 2, "dim_head": 16,
                         "predict_coords": True,
                         "structure_module_depth": 1, "bfloat16": False},
               "data": {"crop_len": 24, "msa_depth": 1, "batch_size": 1},
               "train": {"num_steps": 2, "log_every": 1,
                         "grad_accum_every": 2,
                         "checkpoint_dir": str(tmp_path / "ck")}}
        cfg_path = str(tmp_path / "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        train_main(["--config", cfg_path, "--pdb", fixture])

        metrics = eval_main(["--pdb", fixture, "--config", cfg_path,
                             "--checkpoint", str(tmp_path / "ck"),
                             "--recycles", "0"])
        assert np.isfinite(metrics["kabsch_rmsd"])
        assert metrics["checkpoint"] == str(tmp_path / "ck")
