"""README-era efficient-attention menu wired into the model
(reference README.md:388-487: sparse_self_attn / cross_attn_linear /
cross_attn_kron / cross_attn_compress_ratio patterns).

Covers: per-layer interleaving (the README.md:415 `(True, False) * 6`
pattern), dense-mask equivalence of the sparse variant, scan/unrolled
parity for a uniform menu, conflict detection, and the config-file path
used by scripts/train_distogram.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.config import ModelConfig
from alphafold2_tpu.model.attention_variants import BlockSparseAttention
from alphafold2_tpu.model.evoformer import Evoformer
from alphafold2_tpu.model.primitives import Attention

from conftest import perturb_params


def _inputs(n=32, rows=3, key=0):
    k = jax.random.PRNGKey(key)
    seq = jax.random.randint(k, (1, n), 0, 21)
    msa = jax.random.randint(k, (1, rows, n), 0, 21)
    return seq, msa, jnp.ones((1, n), bool), jnp.ones((1, rows, n), bool)


def _distogram(out):
    return out if isinstance(out, jnp.ndarray) else out.distance


@pytest.mark.quick
def test_interleaved_sparse_full_trunk():
    """The README.md:415 pattern: alternate sparse and full layers."""
    seq, msa, mask, msa_mask = _inputs()
    model = Alphafold2(dim=32, depth=4, heads=2, dim_head=16,
                       sparse_self_attn=(True, False) * 2)
    params = model.init(jax.random.PRNGKey(1), seq, msa=msa, mask=mask,
                        msa_mask=msa_mask)
    out = _distogram(model.apply(params, seq, msa=msa, mask=mask,
                                 msa_mask=msa_mask))
    assert out.shape == (1, 32, 32, 37)
    assert bool(jnp.isfinite(out).all())
    # heterogeneous menu runs unrolled: per-layer param scopes exist and
    # only the sparse layers carry the variant row attention
    layers = params["params"]["net"]
    assert "layers_0" in layers and "layers_3" in layers
    assert "row_norm" in layers["layers_0"]["msa_attn"]      # sparse layer
    assert "row_norm" not in layers["layers_1"]["msa_attn"]  # full layer
    # gradients flow through every layer
    g = jax.grad(lambda p: _distogram(model.apply(
        p, seq, msa=msa, mask=mask, msa_mask=msa_mask)).sum())(params)
    for i in range(4):
        gi = sum(float(jnp.abs(l).sum()) for l in
                 jax.tree.leaves(g["params"]["net"][f"layers_{i}"]))
        assert gi > 0, f"no gradient through layer {i}"


def test_sparse_all_active_equals_dense_attention():
    """With the window covering every block, BlockSparseAttention's
    pattern is all-ones and the module must equal plain gated Attention
    on the same (shared) params — the dense-mask equivalence check."""
    n, dim = 64, 32
    x = jax.random.normal(jax.random.PRNGKey(0), (2, n, dim))
    mask = jnp.arange(n)[None, :] < jnp.array([[n], [n - 10]])[:, 0, None]
    bsa = BlockSparseAttention(dim=dim, heads=2, dim_head=16, block=16,
                               num_global=1, window=n // 16)
    params = perturb_params(bsa.init(jax.random.PRNGKey(1), x, mask=mask),
                            jax.random.PRNGKey(2))
    out_sparse = bsa.apply(params, x, mask=mask)
    dense = Attention(dim=dim, heads=2, dim_head=16)
    out_dense = dense.apply({"params": params["params"]["attn"]}, x,
                            mask=mask)
    # masked-query rows are unspecified on both paths; compare valid rows
    valid = np.asarray(mask)[..., None]
    np.testing.assert_allclose(np.asarray(out_sparse) * valid,
                               np.asarray(out_dense) * valid,
                               atol=2e-5)


def test_uniform_menu_scan_matches_unrolled():
    """A uniform (scannable) variant trunk equals the unrolled trunk on
    re-keyed params — the menu composes with the scan machinery."""
    kw = dict(dim=16, depth=3, heads=2, dim_head=8, linear_attn=True)
    ev_scan = Evoformer(use_scan=True, **kw)
    ev_loop = Evoformer(use_scan=False, **kw)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 6, 16))
    m = jax.random.normal(jax.random.PRNGKey(1), (1, 3, 6, 16))
    p_scan = ev_scan.init(jax.random.PRNGKey(2), x, m)
    stacked = p_scan["params"]["layers"]["block"]
    p_loop = {"params": {}}
    for i in range(3):
        p_loop["params"][f"layers_{i}"] = jax.tree.map(
            lambda t, i=i: t[i], stacked)
    xs, ms = ev_scan.apply(p_scan, x, m)
    xl, ml = ev_loop.apply(p_loop, x, m)
    np.testing.assert_allclose(xs, xl, atol=1e-5)
    np.testing.assert_allclose(ms, ml, atol=1e-5)


def test_conflicting_variants_rejected():
    seq, msa, mask, msa_mask = _inputs(n=16)
    model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16,
                       sparse_self_attn=True, linear_attn=True)
    with pytest.raises(AssertionError, match="conflicting"):
        model.init(jax.random.PRNGKey(1), seq, msa=msa, mask=mask,
                   msa_mask=msa_mask)


def test_menu_incompatible_with_pipeline_and_reversible():
    seq, msa, mask, msa_mask = _inputs(n=16)
    for extra in (dict(reversible=True),
                  dict(pipeline_stages=2)):
        model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16,
                           sparse_self_attn=True, **extra)
        with pytest.raises(AssertionError, match="menu"):
            model.init(jax.random.PRNGKey(1), seq, msa=msa, mask=mask,
                       msa_mask=msa_mask)


def test_config_file_builds_menu_trunk_and_trains():
    """The scripts/train_distogram.py path: a ModelConfig carrying the
    menu (as JSON lists) builds and takes one finite train step."""
    from alphafold2_tpu.data.synthetic import synthetic_batch
    from alphafold2_tpu.train import TrainState, adam, make_train_step

    cfg = ModelConfig(dim=32, depth=2, heads=2, dim_head=16,
                      sparse_self_attn=[True, False], bfloat16=False)
    model = cfg.build()
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=32,
                            msa_depth=3, with_coords=True)
    params = model.init(jax.random.PRNGKey(1), batch["seq"],
                        msa=batch["msa"], mask=batch["mask"],
                        msa_mask=batch["msa_mask"])
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=adam(1e-3), rng=jax.random.PRNGKey(2))
    state, metrics = jax.jit(make_train_step(model))(state, batch)
    assert bool(jnp.isfinite(metrics["loss"]))


def test_kron_and_compress_variants_run():
    seq, msa, mask, msa_mask = _inputs()
    for menu in (dict(kron_attn=True), dict(kv_compress_ratio=2)):
        model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16, **menu)
        params = model.init(jax.random.PRNGKey(1), seq, msa=msa,
                            mask=mask, msa_mask=msa_mask)
        out = _distogram(model.apply(params, seq, msa=msa, mask=mask,
                                     msa_mask=msa_mask))
        assert bool(jnp.isfinite(out).all())


class TestPerformer:
    """FAVOR+ (reference README.md:419-449 cross_attn_linear)."""

    @pytest.mark.quick
    def test_favor_error_shrinks_with_features(self):
        """The FAVOR+ estimator phi(q)^T phi(k) is an unbiased softmax-
        kernel approximation: attention weights converge to the exact
        softmax as nb_features grows."""
        from alphafold2_tpu.model.attention_variants import (
            favor_softmax_features, orthogonal_random_features)

        d, n = 32, 24
        kq, kk = jax.random.split(jax.random.PRNGKey(0))
        # moderate logit scale: FAVOR+'s variance grows with how peaked
        # the softmax is; this tests convergence, not the extreme tail
        q = jax.random.normal(kq, (n, d)) * 0.4
        k = jax.random.normal(kk, (n, d)) * 0.4
        scale = d ** 0.25
        exact = jax.nn.softmax(q @ k.T / jnp.sqrt(d), axis=-1)

        def approx_err(m, seed):
            proj = orthogonal_random_features(jax.random.PRNGKey(seed), m, d)
            pq = favor_softmax_features(q / scale, proj, is_query=True)
            pk = favor_softmax_features(k / scale, proj, is_query=False)
            num = pq @ pk.T
            approx = num / num.sum(-1, keepdims=True)
            return float(jnp.abs(approx - exact).max())

        errs_small = np.mean([approx_err(32, s) for s in range(5)])
        errs_big = np.mean([approx_err(2048, s) for s in range(5)])
        assert errs_big < errs_small * 0.5, (errs_small, errs_big)
        assert errs_big < 0.02, errs_big

    def test_menu_linear_uses_favor_and_runs(self):
        seq, msa, mask, msa_mask = _inputs()
        model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16,
                           linear_attn=True)  # kind defaults to "favor"
        # perturb off init: the zero-init output projections would make
        # every row-attention backend contribute exactly zero
        params = perturb_params(
            model.init(jax.random.PRNGKey(1), seq, msa=msa, mask=mask,
                       msa_mask=msa_mask), jax.random.PRNGKey(9))
        out = _distogram(model.apply(params, seq, msa=msa, mask=mask,
                                     msa_mask=msa_mask))
        assert bool(jnp.isfinite(out).all())
        # elu fallback is a distinct backend: same params shapes, but the
        # computation differs
        model_elu = Alphafold2(dim=32, depth=2, heads=2, dim_head=16,
                               linear_attn=True, linear_attn_kind="elu")
        out_elu = _distogram(model_elu.apply(params, seq, msa=msa,
                                             mask=mask, msa_mask=msa_mask))
        assert bool(jnp.isfinite(out_elu).all())
        assert float(jnp.abs(out - out_elu).max()) > 1e-6

    def test_redraw_hook(self):
        """rngs={'performer': key} redraws features; no rng = fixed."""
        from alphafold2_tpu.model.attention_variants import (
            PerformerAttention)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32))
        mod = PerformerAttention(dim=32, heads=2, dim_head=16,
                                 nb_features=32)
        params = perturb_params(mod.init(jax.random.PRNGKey(1), x),
                                jax.random.PRNGKey(2))
        a = mod.apply(params, x)
        b = mod.apply(params, x)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        r1 = mod.apply(params, x, rngs={"performer": jax.random.PRNGKey(3)})
        r2 = mod.apply(params, x, rngs={"performer": jax.random.PRNGKey(4)})
        assert float(jnp.abs(r1 - r2).max()) > 1e-6

    def test_favor_batch_isolation(self):
        """Regression: the key stabilizer is per attention instance, so a
        high-magnitude batch entry must not degrade a low-scale entry's
        approximation (a global key max crushed the cold entry's features
        toward the eps floor)."""
        from alphafold2_tpu.model.attention_variants import (
            favor_softmax_features, orthogonal_random_features)

        d, n, m = 32, 16, 2048
        kq, kk = jax.random.split(jax.random.PRNGKey(2))
        scale = d ** 0.25
        q_cold = jax.random.normal(kq, (1, n, d)) * 0.3
        k_cold = jax.random.normal(kk, (1, n, d)) * 0.3
        q_hot, k_hot = q_cold * 6.0, k_cold * 6.0  # ~tens of nats hotter
        proj = orthogonal_random_features(jax.random.PRNGKey(3), m, d)

        def cold_err(qb, kb):
            pq = favor_softmax_features(qb / scale, proj, is_query=True)
            pk = favor_softmax_features(kb / scale, proj, is_query=False)
            num = pq @ jnp.swapaxes(pk, -1, -2)
            approx = num / num.sum(-1, keepdims=True)
            exact = jax.nn.softmax(
                qb @ jnp.swapaxes(kb, -1, -2) / jnp.sqrt(d), axis=-1)
            return float(jnp.abs(approx - exact)[0].max())

        alone = cold_err(q_cold, k_cold)
        batched = cold_err(jnp.concatenate([q_cold, q_hot]),
                           jnp.concatenate([k_cold, k_hot]))
        # cold entry's error must be unchanged by the hot neighbor
        assert batched < alone * 1.5 + 1e-3, (alone, batched)
