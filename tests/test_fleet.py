"""Fleet tier tests (ISSUE 4): replica registry + rollout epochs,
consistent-hash routing (determinism, rebalance bounds, health walks),
the npz-over-HTTP peer cache tier (hit/miss/409/corruption/failure
markdown), the shared-volume object-store tier, coalescing leader
promotion, and the two-replica in-process fleet end-to-end (route ->
fleet-wide coalesce, owner-down local fallback, peer fetch feeding the
local tiers, epoch-bump invalidation with zero stale-tag hits).

The unit tier is no-model and (mostly) no-network; the peer-protocol
tests use real localhost HTTP but no model; only the end-to-end class
folds through a tiny Alphafold2 — everything stays in tier-1 (CPU,
`-m 'not slow'`).
"""

import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from alphafold2_tpu import fleet
from alphafold2_tpu.cache import (FoldCache, InflightRegistry, decode_fold,
                                  encode_fold, fold_key)
from alphafold2_tpu.cache.store import CachedFold
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, FoldRequest, Scheduler,
                                  SchedulerConfig)

MSA_DEPTH = 3


def fold_value(n=6, seed=0):
    rng = np.random.default_rng(seed)
    return CachedFold(rng.normal(size=(n, 3)).astype(np.float32),
                      rng.uniform(size=(n,)).astype(np.float32))


@pytest.mark.quick
class TestRolloutState:
    def test_bump_epochs_and_subscribers(self):
        st = fleet.RolloutState("v1", registry=MetricsRegistry())
        seen = []
        st.subscribe(lambda tag, epoch: seen.append((tag, epoch)))
        assert st.current() == ("v1", 0)
        assert st.bump("v2") == 1
        assert st.current() == ("v2", 1)
        # idempotent re-announce of the current tag: no epoch churn
        assert st.bump("v2") == 1
        assert seen == [("v2", 1)]

    def test_broken_subscriber_never_blocks_rollout(self):
        st = fleet.RolloutState("v1", registry=MetricsRegistry())
        st.subscribe(lambda tag, epoch: 1 / 0)
        assert st.bump("v2") == 1


@pytest.mark.quick
class TestReplicaRegistry:
    def test_membership_epoch_bumps_on_change_only(self):
        reg = fleet.ReplicaRegistry(registry=MetricsRegistry())
        e0 = reg.epoch
        reg.register("a")
        reg.register("b")
        assert reg.epoch == e0 + 2
        reg.mark("a", up=False)
        e1 = reg.epoch
        reg.mark("a", up=False)          # no change, no bump
        assert reg.epoch == e1
        reg.heartbeat("b")               # freshness, not membership
        assert reg.epoch == e1
        reg.deregister("b")
        assert reg.epoch == e1 + 1
        assert reg.member_ids() == ["a"]

    def test_heartbeat_timeout_health(self):
        clock = [0.0]
        reg = fleet.ReplicaRegistry(heartbeat_timeout_s=5.0,
                                    clock=lambda: clock[0],
                                    registry=MetricsRegistry())
        reg.register("a")
        assert reg.is_healthy("a")
        clock[0] = 4.0
        assert reg.is_healthy("a")
        clock[0] = 6.0
        assert not reg.is_healthy("a")   # stale heartbeat
        reg.heartbeat("a")
        assert reg.is_healthy("a")
        reg.mark("a", up=False)          # admin mark beats freshness
        assert not reg.is_healthy("a")


@pytest.mark.quick
class TestConsistentHashRouter:
    def _fleet(self, ids=("a", "b", "c")):
        reg = fleet.ReplicaRegistry(registry=MetricsRegistry())
        for rid in ids:
            reg.register(rid)
        return reg

    def test_deterministic_across_router_instances(self):
        reg = self._fleet()
        ra = fleet.ConsistentHashRouter(reg, "a",
                                        metrics=MetricsRegistry())
        rb = fleet.ConsistentHashRouter(reg, "b",
                                        metrics=MetricsRegistry())
        keys = [f"key{i}" for i in range(200)]
        # every replica computes the same ownership map (blake2b, not
        # process-seeded hash()) — the property fleet-wide coalescing
        # rests on
        assert [ra.owner_for(k) for k in keys] \
            == [rb.owner_for(k) for k in keys]

    def test_rebalance_moves_only_departed_keys(self):
        reg = self._fleet()
        router = fleet.ConsistentHashRouter(reg, "a",
                                            metrics=MetricsRegistry())
        keys = [f"key{i}" for i in range(400)]
        before = {k: router.owner_for(k) for k in keys}
        reg.deregister("c")
        after = {k: router.owner_for(k) for k in keys}
        # consistent hashing's contract: keys NOT owned by the departed
        # replica keep their owner
        for k in keys:
            if before[k] != "c":
                assert after[k] == before[k]
        assert all(o in ("a", "b") for o in after.values())

    def test_unhealthy_owner_skipped_and_empty_ring(self):
        reg = self._fleet(("a", "b"))
        router = fleet.ConsistentHashRouter(reg, "a",
                                            metrics=MetricsRegistry())
        k = next(f"key{i}" for i in range(1000)
                 if router.owner_for(f"key{i}") == "b")
        reg.mark("b", up=False)
        assert router.owner_for(k) == "a"
        reg.mark("a", up=False)
        assert router.owner_for(k) is None
        assert router.route(k).is_local   # never errors, always a seat

    def test_route_decisions(self):
        reg = self._fleet(("a", "b"))
        router = fleet.ConsistentHashRouter(reg, "a",
                                            metrics=MetricsRegistry())
        k_local = next(f"key{i}" for i in range(1000)
                       if router.owner_for(f"key{i}") == "a")
        k_remote = next(f"key{i}" for i in range(1000)
                        if router.owner_for(f"key{i}") == "b")
        assert router.route(k_local).reason == "local_owner"
        # b exposes no submit transport: local fold, reason says why
        d = router.route(k_remote)
        assert d.is_local and d.reason == "not_forwardable"
        tickets = []
        reg.get("b").submit = lambda req: tickets.append(req) or "ticket"
        d = router.route(k_remote)
        assert not d.is_local and d.reason == "forward"
        assert router.forward("b", "the-request") == "ticket"
        assert tickets == ["the-request"]


@pytest.mark.quick
class TestObjectStoreTier:
    def test_filesystem_roundtrip_and_corruption(self, tmp_path):
        store = fleet.FilesystemObjectStore(str(tmp_path))
        v = fold_value()
        store.put("k1", encode_fold("k1", v))
        assert decode_fold("k1", store.get("k1")).coords.shape == (6, 3)
        assert store.get("absent") is None
        assert len(store) == 1
        peer = fleet.ObjectStorePeer(store, metrics=MetricsRegistry())
        got = peer.get("k1")
        assert np.allclose(got.coords, v.coords)
        # corrupt object: miss, and deleted so the fleet stops re-parsing
        store.put("bad", b"not an npz")
        assert peer.get("bad") is None
        assert store.get("bad") is None

    def test_fold_cache_write_through_shares_across_replicas(
            self, tmp_path):
        store = fleet.FilesystemObjectStore(str(tmp_path))
        reg = MetricsRegistry()
        a = FoldCache(peer=fleet.ObjectStorePeer(store, metrics=reg),
                      peer_write_through=True, registry=reg)
        b = FoldCache(peer=fleet.ObjectStorePeer(store, metrics=reg),
                      registry=reg)
        v = fold_value(n=5, seed=3)
        a.put("k", v.coords, v.confidence)
        got = b.get("k")                  # b never folded: shared-store hit
        assert got is not None and np.allclose(got.coords, v.coords)
        assert b.stats.snapshot()["peer_hits"] == 1
        assert b.get("k") is not None     # promoted into b's memory tier
        assert b.stats.snapshot()["peer_hits"] == 1


class TestPeerProtocol:
    """Real localhost HTTP, no model: the npz-over-HTTP tier."""

    def _wire(self, model_tag="v1"):
        reg = fleet.ReplicaRegistry(model_tag=model_tag,
                                    registry=MetricsRegistry())
        owner_cache = FoldCache(registry=MetricsRegistry())
        srv = fleet.PeerCacheServer(owner_cache, rollout=reg.rollout,
                                    replica_id="r1",
                                    metrics=MetricsRegistry()).start()
        reg.register("r0")
        reg.register("r1", peer_addr=srv.address)
        router = fleet.ConsistentHashRouter(reg, "r0",
                                            metrics=MetricsRegistry())
        client = fleet.PeerCacheClient(reg, "r0", router=router,
                                       rollout=reg.rollout,
                                       metrics=MetricsRegistry())
        local = FoldCache(peer=client, registry=MetricsRegistry())
        k = next(f"key{i}" for i in range(1000)
                 if router.owner_for(f"key{i}") == "r1")
        return reg, owner_cache, srv, client, local, k

    def test_remote_hit_promotes_into_local_memory(self):
        reg, owner_cache, srv, client, local, k = self._wire()
        try:
            v = fold_value(n=7, seed=1)
            owner_cache.put(k, v.coords, v.confidence)
            got = local.get(k)
            assert got is not None and np.allclose(got.coords, v.coords)
            snap = local.stats.snapshot()
            assert snap["peer_hits"] == 1 and snap["hits"] == 1
            # second get: memory tier, no second fetch
            assert local.get(k) is not None
            assert local.stats.snapshot()["peer_hits"] == 1
        finally:
            srv.stop()

    def test_miss_and_owner_side_keys(self):
        reg, owner_cache, srv, client, local, k = self._wire()
        try:
            assert local.get(k) is None               # clean remote miss
            assert local.stats.snapshot()["misses"] == 1
        finally:
            srv.stop()

    def test_stale_tag_rejected_409(self):
        reg, owner_cache, srv, client, local, k = self._wire()
        try:
            v = fold_value()
            owner_cache.put(k, v.coords, v.confidence)
            host, port = srv.address
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://{host}:{port}/cache/{k}?tag=WRONG",
                    timeout=5)
            assert ei.value.code == 409
            # a straggler client still on the old tag after a fleet
            # bump gets misses, never stale folds
            straggler = fleet.PeerCacheClient(
                reg, "r0", rollout=fleet.RolloutState(
                    "old", registry=MetricsRegistry()),
                metrics=MetricsRegistry())
            assert straggler.get(k) is None
            assert straggler.stale_tag_hits == 0
        finally:
            srv.stop()

    def test_corrupt_bytes_is_miss_not_error(self):
        # a hostile/buggy peer returning 200 with garbage: the client's
        # decode_fold validation turns it into a clean miss and does
        # NOT mark the (transport-healthy) peer down
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        class _Garbage(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                body = b"definitely not an npz"
                self.send_response(200)
                self.send_header("X-Model-Tag", "v1")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Garbage)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            reg = fleet.ReplicaRegistry(model_tag="v1",
                                        registry=MetricsRegistry())
            reg.register("r0")
            host, port = httpd.server_address[:2]
            reg.register("r1", peer_addr=(str(host), int(port)))
            client = fleet.PeerCacheClient(reg, "r0",
                                           rollout=reg.rollout,
                                           metrics=MetricsRegistry())
            local = FoldCache(peer=client, registry=MetricsRegistry())
            k = next(f"key{i}" for i in range(1000)
                     if client.router.owner_for(f"key{i}") == "r1")
            assert local.get(k) is None
            assert reg.is_healthy("r1")
            assert local.stats.snapshot()["misses"] == 1
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_transport_failures_mark_owner_down(self):
        reg, owner_cache, srv, client, local, k = self._wire()
        srv.stop()                        # owner gone; registry not told
        for _ in range(client.fail_threshold):
            assert local.get(k) is None
        # consecutive transport failures marked it down: routing (and
        # further peer fetches) now skip it
        assert not reg.is_healthy("r1")
        assert client.router.owner_for(k) == "r0"


class _OkExecutor:
    """Stub executor: deterministic coords, optional pre-run delay."""

    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s
        self.calls = 0

    def run(self, batch, num_recycles):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.calls += 1
        b, n = batch["seq"].shape

        class R:
            coords = np.zeros((b, n, 3), np.float32)
            confidence = np.full((b, n), 0.5, np.float32)

        return R()

    def stats(self):
        return {"calls": self.calls}


@pytest.mark.quick
class TestLeaderPromotion:
    def test_registry_promote_picks_and_keeps_rest_parked(self):
        reg = InflightRegistry(registry=MetricsRegistry())
        assert reg.attach("k", "leader")
        assert not reg.attach("k", "f1")
        assert not reg.attach("k", "f2")
        promoted = reg.promote("k", lambda fs: fs[-1])
        assert promoted == "f2"
        assert reg.waiting() == 1          # f1 still parked
        # later attachers see the NEW leader
        is_leader, leader = reg.attach_with_leader("k", "f3")
        assert not is_leader and leader == "f2"
        assert sorted(reg.settle("k")) == ["f1", "f3"]
        assert reg.snapshot()["leader_promotions"] == 1

    def test_promote_with_no_followers_dissolves_group(self):
        reg = InflightRegistry(registry=MetricsRegistry())
        assert reg.attach("k", "leader")
        assert reg.promote("k", lambda fs: fs[0]) is None
        assert reg.attach("k", "fresh")    # next attach leads again
        assert reg.snapshot()["leader_promotions"] == 0

    def test_shed_leader_promotes_tightest_deadline_follower(self):
        policy = BucketPolicy((16,))
        config = SchedulerConfig(max_batch_size=4, max_wait_ms=600.0,
                                 poll_ms=5.0, msa_depth=0)
        cache = FoldCache(registry=MetricsRegistry())
        sched = Scheduler(_OkExecutor(), policy, config, cache=cache,
                          model_tag="promo", registry=MetricsRegistry())
        seq = np.arange(12, dtype=np.int32) % 20
        with sched:
            # leader's deadline expires while queued (batch of 4 never
            # fills, max_wait 600ms not reached at shed time)
            t_lead = sched.submit(FoldRequest(seq=seq, deadline_s=0.15))
            t_tight = sched.submit(FoldRequest(seq=seq, deadline_s=5.0))
            t_loose = sched.submit(FoldRequest(seq=seq))   # no deadline
            r_lead = t_lead.result(timeout=10)
            r_tight = t_tight.result(timeout=10)
            r_loose = t_loose.result(timeout=10)
        assert r_lead.status == "shed"
        # the group survived its leader: the tightest-deadline follower
        # folded as the new leader, the loose one settled off it
        assert r_tight.ok and r_tight.source == "fold"
        assert r_loose.ok and r_loose.source == "coalesced"
        assert np.allclose(r_tight.coords, r_loose.coords)
        assert sched._inflight.snapshot()["leader_promotions"] == 1


@pytest.fixture(scope="module")
def model_and_params():
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu import Alphafold2

    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1)
    n = 16
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
        mask=jnp.ones((1, n), bool),
        msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
    return model, params


def _two_replica_fleet(model_and_params, **kwargs):
    from alphafold2_tpu import serve

    model, params = model_and_params
    policy = BucketPolicy((16,))
    config = SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                             msa_depth=MSA_DEPTH, poll_ms=2.0)
    return fleet.InProcessFleet(
        lambda: serve.FoldExecutor(model, params, max_entries=2),
        policy, config, n_replicas=2, **kwargs)


def _request(seed=0, n=12):
    rng = np.random.default_rng(seed)
    return FoldRequest(
        seq=rng.integers(0, 20, size=n).astype(np.int32),
        msa=rng.integers(0, 20, size=(MSA_DEPTH, n)).astype(np.int32))


def _key_for(fl, req):
    cfg = fl.replicas[0].scheduler.config
    return fold_key(req.seq, req.msa, msa_depth=cfg.msa_depth,
                    num_recycles=cfg.num_recycles,
                    model_tag=fl.replicas[0].scheduler.model_tag)


class TestTwoReplicaFleet:
    def test_duplicates_across_replicas_fold_once(self, model_and_params):
        with _two_replica_fleet(model_and_params, model_tag="v1") as fl:
            req = _request(seed=1)
            dup = FoldRequest(seq=req.seq, msa=req.msa)
            t0 = fl.submit(req, replica=0)
            t1 = fl.submit(dup, replica=1)
            a, b = t0.result(timeout=120), t1.result(timeout=120)
            assert a.ok and b.ok
            assert np.allclose(a.coords, b.coords)
            agg = fl.stats()["aggregate"]
            # one of the two submits crossed a replica boundary (routing
            # owns the key on exactly one side); fleet-wide the work ran
            # once
            assert agg["batches"] == 1
            assert agg["cache_hits"] + agg["coalesced"] == 1
            assert {a.source, b.source} <= {"fold", "forwarded",
                                            "cache", "coalesced"}

    def test_owner_down_local_fallback(self, model_and_params):
        with _two_replica_fleet(model_and_params, model_tag="v1") as fl:
            # find a request owned by r1 as seen from r0, then take r1
            # down: r0 must fold it locally, not error
            router = fl.replicas[0].router
            req = next(r for r in (_request(seed=s) for s in range(50))
                       if router.owner_for(_key_for(fl, r)) == "r1")
            fl.mark("r1", up=False)
            resp = fl.submit(req, replica=0).result(timeout=120)
            assert resp.ok and resp.source == "fold"
            assert fl.stats()["replicas"]["r0"]["served"] == 1

    def test_forward_transport_error_falls_back_local(
            self, model_and_params):
        with _two_replica_fleet(model_and_params, model_tag="v1") as fl:
            router = fl.replicas[0].router
            req = next(r for r in (_request(seed=s) for s in range(50))
                       if router.owner_for(_key_for(fl, r)) == "r1")

            class _Broken:
                def submit(self, request, trace=None):
                    raise ConnectionError("transport down")

            fl.registry.get("r1").transport = _Broken()
            resp = fl.submit(req, replica=0).result(timeout=120)
            assert resp.ok and resp.source == "fold"

    def test_peer_fetch_feeds_local_memory_tier(self, model_and_params):
        with _two_replica_fleet(model_and_params, model_tag="v1") as fl:
            router = fl.replicas[0].router
            req = next(r for r in (_request(seed=s) for s in range(50))
                       if router.owner_for(_key_for(fl, r)) == "r1")
            k = _key_for(fl, req)
            # owner folds it through its own front door (no forwarding)
            assert fl.submit(req, replica=1).result(timeout=120).ok
            # r0 never folded the key: its cache answers via the peer
            # tier and promotes into local memory
            got = fl.replicas[0].cache.get(k)
            assert got is not None
            snap = fl.replicas[0].cache.stats.snapshot()
            assert snap["peer_hits"] == 1
            assert fl.replicas[0].cache.get(k) is not None
            assert fl.replicas[0].cache.stats.snapshot()["peer_hits"] == 1

    def test_epoch_bump_invalidates_old_tag_everywhere(
            self, model_and_params):
        with _two_replica_fleet(model_and_params, model_tag="v1") as fl:
            req = _request(seed=9)
            k_v1 = _key_for(fl, req)
            assert fl.submit(req, replica=0).result(timeout=120).ok
            assert fl.submit(
                FoldRequest(seq=req.seq, msa=req.msa),
                replica=0).result(timeout=120).source == "cache"

            epoch = fl.bump_model_tag("v2")
            assert epoch == 1
            # every scheduler re-keyed before bump() returned
            assert all(r.scheduler.model_tag == "v2"
                       for r in fl.replicas)
            # same content now folds fresh: old-tag entries unreachable
            resp = fl.submit(FoldRequest(seq=req.seq, msa=req.msa),
                             replica=0).result(timeout=120)
            assert resp.ok and resp.source in ("fold", "forwarded")
            # peer protocol refuses the old tag outright: a straggler
            # client still keyed to v1 sees misses, zero stale hits
            straggler = fleet.PeerCacheClient(
                fl.registry, "r0",
                rollout=fleet.RolloutState("v1",
                                           registry=MetricsRegistry()),
                metrics=MetricsRegistry())
            assert straggler.get(k_v1) is None
            assert straggler.stale_tag_hits == 0
            for replica in fl.replicas:
                client = replica.cache.peer
                assert client is None or client.stale_tag_hits == 0
