"""Pallas fused-attention kernel tests (interpreter mode on CPU; the same
kernel lowers to Mosaic on TPU) and the model-path backend switch."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.ops import attention as ops_attn


def make_inputs(key, b=4, n=64, d=32):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (b, n, d)) * 0.5
    k = jax.random.normal(ks[1], (b, n, d)) * 0.5
    v = jax.random.normal(ks[2], (b, n, d))
    bias = jax.random.normal(ks[3], (b, n, n))
    return q, k, v, bias


class TestFusedAttention:
    def test_matches_reference(self):
        q, k, v, bias = make_inputs(jax.random.PRNGKey(0))
        out = ops_attn.fused_attention(q, k, v, bias, interpret=True)
        ref = ops_attn.attention_reference(q, k, v, bias)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_blocked_queries(self):
        q, k, v, bias = make_inputs(jax.random.PRNGKey(1), n=128)
        out = ops_attn.fused_attention(q, k, v, bias, block_q=32,
                                       interpret=True)
        ref = ops_attn.attention_reference(q, k, v, bias)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_masked_bias(self):
        q, k, v, bias = make_inputs(jax.random.PRNGKey(2))
        bias = bias.at[:, :, 48:].set(-1e9)  # mask the key tail
        out = ops_attn.fused_attention(q, k, v, bias, interpret=True)
        ref = ops_attn.attention_reference(q, k, v, bias)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        assert bool(jnp.isfinite(out).all())

    def test_bf16_inputs(self):
        q, k, v, bias = make_inputs(jax.random.PRNGKey(3))
        qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
        out = ops_attn.fused_attention(qb, kb, vb, bias, interpret=True)
        ref = ops_attn.attention_reference(qb, kb, vb, bias)
        assert out.dtype == jnp.bfloat16
        assert np.allclose(np.asarray(out, np.float32),
                           np.asarray(ref, np.float32), atol=3e-2)

    def test_cross_attention_lengths(self):
        q, _, _, _ = make_inputs(jax.random.PRNGKey(4), n=64)
        _, k, v, _ = make_inputs(jax.random.PRNGKey(5), n=32)
        bias = jnp.zeros((4, 64, 32))
        out = ops_attn.fused_attention(q, k, v, bias, interpret=True)
        ref = ops_attn.attention_reference(q, k, v, bias)
        assert out.shape == (4, 64, 32)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_no_bias_no_mask(self):
        # the lean path: no dense bias tensor is ever allocated
        q, k, v, _ = make_inputs(jax.random.PRNGKey(6))
        out = ops_attn.fused_attention(q, k, v, interpret=True)
        ref = ops_attn.attention_reference(q, k, v)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_mask_vectors_expand_in_kernel(self):
        # masks arrive as (B//heads, N) vectors; fill happens in VMEM
        b, h, n, d = 2, 2, 64, 32
        q, k, v, _ = make_inputs(jax.random.PRNGKey(7), b=b * h, n=n, d=d)
        km = jnp.arange(n)[None, :] < jnp.array([[40], [56]])  # (b, n)
        qm = jnp.arange(n)[None, :] < jnp.array([[64], [48]])
        out = ops_attn.fused_attention(q, k, v, q_mask=qm, k_mask=km,
                                       heads=h, interpret=True)
        ref = ops_attn.attention_reference(q, k, v, q_mask=qm, k_mask=km,
                                           heads=h)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        # fully-masked query rows are finite (uniform softmax), not NaN
        assert bool(jnp.isfinite(out).all())

    def test_unrepeated_bias_index_map(self):
        # bias (batch*heads, nq, nk) is replayed over the folded axial
        # axis purely via the BlockSpec index map — the axial layout
        # B = batch * repeat * heads, head fastest
        batch, repeat, h, n, d = 2, 4, 2, 32, 16
        b_all = batch * repeat * h
        keys = jax.random.split(jax.random.PRNGKey(8), 4)
        q = jax.random.normal(keys[0], (b_all, n, d)) * 0.5
        k = jax.random.normal(keys[1], (b_all, n, d)) * 0.5
        v = jax.random.normal(keys[2], (b_all, n, d))
        bias = jax.random.normal(keys[3], (batch * h, n, n))
        out = ops_attn.fused_attention(q, k, v, bias, heads=h,
                                       bias_repeat=repeat, interpret=True)
        ref = ops_attn.attention_reference(q, k, v, bias, heads=h,
                                           bias_repeat=repeat)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_bias_and_masks_together(self):
        batch, repeat, h, n, d = 1, 2, 2, 32, 16
        b_all = batch * repeat * h
        keys = jax.random.split(jax.random.PRNGKey(9), 4)
        q = jax.random.normal(keys[0], (b_all, n, d)) * 0.5
        k = jax.random.normal(keys[1], (b_all, n, d)) * 0.5
        v = jax.random.normal(keys[2], (b_all, n, d))
        bias = jax.random.normal(keys[3], (batch * h, n, n))
        km = jnp.arange(n)[None, :] < 24
        km = jnp.broadcast_to(km, (batch * repeat, n))
        out = ops_attn.fused_attention(q, k, v, bias, k_mask=km, heads=h,
                                       bias_repeat=repeat, interpret=True)
        ref = ops_attn.attention_reference(q, k, v, bias, k_mask=km,
                                           heads=h, bias_repeat=repeat)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


class TestBackendSwitch:
    def test_flag_roundtrip(self):
        assert not ops_attn.pallas_attention_enabled()
        with ops_attn.pallas_attention(True):
            assert ops_attn.pallas_attention_enabled()
        assert not ops_attn.pallas_attention_enabled()

    def test_model_runs_with_pallas_backend(self, monkeypatch):
        """Run the full model through the Pallas path (interpreter mode on
        CPU) and compare against the XLA path — numerics must agree."""
        monkeypatch.setattr(
            ops_attn, "fused_attention",
            functools.partial(ops_attn.fused_attention, interpret=True))
        from alphafold2_tpu import Alphafold2
        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16)
        seq = jax.random.randint(jax.random.PRNGKey(6), (1, 16), 0, 21)
        msa = jax.random.randint(jax.random.PRNGKey(7), (1, 3, 16), 0, 21)
        params = model.init(jax.random.PRNGKey(8), seq, msa=msa)

        ret_xla = model.apply(params, seq, msa=msa)
        with ops_attn.pallas_attention(True):
            ret_pal = model.apply(params, seq, msa=msa)
        assert np.allclose(np.asarray(ret_xla.distance),
                           np.asarray(ret_pal.distance), atol=2e-3)


class TestBlockSparseKernel:
    """True block-skipping sparse attention (ops/block_sparse.py) vs the
    dense+mask semantics of the model-level BlockSparseAttention."""

    def _pattern(self, nqb, window=1, num_global=1):
        bi = np.arange(nqb)
        local = np.abs(bi[:, None] - bi[None, :]) <= window
        glob = (bi[None, :] < num_global) | (bi[:, None] < num_global)
        return local | glob

    @pytest.mark.quick
    def test_matches_dense_masked_reference(self):
        from alphafold2_tpu.ops.block_sparse import block_sparse_attention

        rng = np.random.default_rng(0)
        b, n, d, blk = 2, 32, 16, 8
        q, k, v = (jnp.asarray(rng.normal(size=(b, n, d)), jnp.float32)
                   for _ in range(3))
        pattern = self._pattern(n // blk)
        out = block_sparse_attention(q, k, v, pattern, block=blk,
                                     scale=1.0, interpret=True)
        tok = np.repeat(np.repeat(pattern, blk, 0), blk, 1)
        bias = jnp.where(jnp.asarray(tok), 0.0, ops_attn.MASK_VALUE)[None]
        ref = ops_attn.attention_reference(
            q, k, v, bias=jnp.broadcast_to(bias, (b, n, n)))
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.quick
    def test_default_scale_is_inv_sqrt_d(self):
        """scale=None applies 1/sqrt(D) inside the kernel — equivalent to
        pre-scaling q (the asymmetric pre-scaled-q-only API invited a
        missing-1/sqrt(d) bug in wiring, round-2 ADVICE)."""
        from alphafold2_tpu.ops.block_sparse import block_sparse_attention

        rng = np.random.default_rng(7)
        b, n, d, blk = 1, 32, 16, 8
        q, k, v = (jnp.asarray(rng.normal(size=(b, n, d)), jnp.float32)
                   for _ in range(3))
        pattern = self._pattern(n // blk)
        out_default = block_sparse_attention(q, k, v, pattern, block=blk,
                                             interpret=True)
        out_prescaled = block_sparse_attention(
            q * d ** -0.5, k, v, pattern, block=blk, scale=1.0,
            interpret=True)
        assert np.allclose(np.asarray(out_default),
                           np.asarray(out_prescaled), atol=1e-6)

    def test_module_kernel_backend_matches_dense(self):
        """BlockSparseAttention with the Pallas backend on (interpret mode
        under CPU) equals its dense+mask path — one params tree, two
        compute backends (mirrors TestBackendSwitch for ops/attention)."""
        from alphafold2_tpu.model import BlockSparseAttention
        from alphafold2_tpu.ops.attention import pallas_attention

        rng = jax.random.PRNGKey(11)
        b, n, dim = 2, 32, 24
        x = jax.random.normal(rng, (b, n, dim), jnp.float32)
        mod = BlockSparseAttention(dim=dim, heads=2, dim_head=8, block=8,
                                   num_global=1, window=1)
        from conftest import perturb_params
        params = perturb_params(mod.init(jax.random.PRNGKey(12), x),
                                jax.random.PRNGKey(13))
        out_dense = mod.apply(params, x)
        assert float(np.abs(np.asarray(out_dense)).max()) > 0
        with pallas_attention(True):
            out_kernel = mod.apply(params, x)
        assert np.allclose(np.asarray(out_dense), np.asarray(out_kernel),
                           atol=1e-4), np.abs(
            np.asarray(out_dense) - np.asarray(out_kernel)).max()

    @pytest.mark.quick
    def test_k_mask_matches_dense(self):
        """Per-key masks inside live blocks (padded crop tails, gaps)
        match the dense -1e9 semantics at valid-query positions."""
        from alphafold2_tpu.ops.block_sparse import block_sparse_attention

        rng = np.random.default_rng(3)
        b, n, d, blk = 2, 32, 16, 8
        q, k, v = (jnp.asarray(rng.normal(size=(b, n, d)), jnp.float32)
                   for _ in range(3))
        # ragged per-sequence validity incl. a fully-masked block
        k_mask = (jnp.ones((b, n), bool)
                  .at[0, 21:].set(False)
                  .at[1, 12:].set(False))
        pattern = self._pattern(n // blk)
        out = block_sparse_attention(q, k, v, pattern, k_mask=k_mask,
                                     block=blk, scale=1.0, interpret=True)
        tok = np.repeat(np.repeat(pattern, blk, 0), blk, 1)
        bias = jnp.where(jnp.asarray(tok), 0.0, ops_attn.MASK_VALUE)[None]
        logits = jnp.einsum("bnd,bmd->bnm", q, k) + bias
        logits = jnp.where(k_mask[:, None, :], logits,
                           ops_attn.MASK_VALUE)
        ref = jnp.einsum("bnm,bmd->bnd", jax.nn.softmax(logits, -1), v)
        # compare only valid-QUERY rows (masked-query rows unspecified)
        for bi, nv in ((0, 21), (1, 12)):
            assert np.allclose(np.asarray(out)[bi, :nv],
                               np.asarray(ref)[bi, :nv], atol=1e-5)

    def test_module_kernel_backend_matches_dense_masked(self):
        """BlockSparseAttention with a token mask no longer falls back:
        kernel path equals the dense+mask path at valid positions."""
        from conftest import perturb_params

        from alphafold2_tpu.model import BlockSparseAttention
        from alphafold2_tpu.ops.attention import pallas_attention

        b, n, dim = 2, 32, 24
        x = jax.random.normal(jax.random.PRNGKey(21), (b, n, dim))
        mask = (jnp.ones((b, n), bool)
                .at[0, 25:].set(False)
                .at[1, 17:].set(False))
        mod = BlockSparseAttention(dim=dim, heads=2, dim_head=8, block=8,
                                   num_global=1, window=1)
        params = perturb_params(mod.init(jax.random.PRNGKey(22), x, mask),
                                jax.random.PRNGKey(23))
        out_dense = mod.apply(params, x, mask)
        with pallas_attention(True):
            out_kernel = mod.apply(params, x, mask)
        valid = np.asarray(mask)[..., None]
        assert float(np.abs(np.asarray(out_dense) * valid).max()) > 0
        assert np.allclose(np.asarray(out_dense) * valid,
                           np.asarray(out_kernel) * valid, atol=1e-4)

    def test_plan_compresses(self):
        from alphafold2_tpu.ops.block_sparse import plan_block_pattern

        # window-only band: every row has <= 3 live blocks of 8, so the
        # schedule runs 3 steps, not 8 — real compute savings
        pattern = self._pattern(8, window=1, num_global=0)
        cols, valid = plan_block_pattern(pattern)
        assert cols.shape[1] == 3
        assert valid.max() == 1

        # with a global row the schedule is bounded by that row's count
        # (it attends everything) but sparse rows stay mostly invalid
        pattern = self._pattern(8, window=1, num_global=1)
        cols, valid = plan_block_pattern(pattern)
        assert cols.shape[1] == 8
        assert valid[4].sum() == 4  # interior row: self, +-1, global

    def test_empty_row_rejected(self):
        from alphafold2_tpu.ops.block_sparse import plan_block_pattern

        bad = np.zeros((4, 4), bool)
        bad[0, 0] = True
        with pytest.raises(ValueError):
            plan_block_pattern(bad)

    def test_wide_pattern_and_bf16(self):
        from alphafold2_tpu.ops.block_sparse import block_sparse_attention

        rng = np.random.default_rng(1)
        b, n, d, blk = 1, 64, 8, 8
        q, k, v = (jnp.asarray(rng.normal(size=(b, n, d)), jnp.bfloat16)
                   for _ in range(3))
        pattern = self._pattern(n // blk, window=2, num_global=2)
        out = block_sparse_attention(q, k, v, pattern, block=blk,
                                     scale=1.0, interpret=True)
        tok = np.repeat(np.repeat(pattern, blk, 0), blk, 1)
        bias = jnp.where(jnp.asarray(tok), 0.0, ops_attn.MASK_VALUE)[None]
        ref = ops_attn.attention_reference(
            q, k, v, bias=jnp.broadcast_to(bias, (b, n, n)))
        # bf16 end-to-end: reference rounds attn weights to bf16 before
        # the PV matmul, the kernel keeps f32 accumulators — one-ulp-of-
        # bf16 disagreement on O(1) outputs
        assert np.allclose(np.asarray(out, jnp.float32),
                           np.asarray(ref, jnp.float32), atol=5e-2)


class TestFusedAttentionGrad:
    """The kernel's custom_vjp (r05): Pallas forward, XLA-recompute
    backward — grads must match plain autodiff of the reference, and the
    train path through the model must differentiate (the round-4 kernel
    had no AD rule at all, so BENCH_PALLAS could never take a train
    step)."""

    def test_grads_match_reference(self):
        q, k, v, bias = make_inputs(jax.random.PRNGKey(7))
        qm = jnp.ones((q.shape[0], q.shape[1])).at[:, -3:].set(0.0)

        def f_kernel(q, k, v, bias):
            out = ops_attn.fused_attention(q, k, v, bias, q_mask=qm,
                                           k_mask=qm, interpret=True)
            return jnp.sum(out * out)

        def f_ref(q, k, v, bias):
            out = ops_attn.attention_reference(q, k, v, bias, q_mask=qm,
                                               k_mask=qm)
            return jnp.sum(out * out)

        gk = jax.grad(f_kernel, argnums=(0, 1, 2, 3))(q, k, v, bias)
        gr = jax.grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_unrepeated_bias_grad_sums_over_fold(self):
        """d_bias must accumulate over the folded axial axis the index
        map replays the bias across."""
        b, rep, h, n, d = 1, 3, 2, 16, 8
        key = jax.random.PRNGKey(8)
        ks = jax.random.split(key, 4)
        q = jax.random.normal(ks[0], (b * rep * h, n, d)) * 0.5
        k = jax.random.normal(ks[1], (b * rep * h, n, d)) * 0.5
        v = jax.random.normal(ks[2], (b * rep * h, n, d))
        bias = jax.random.normal(ks[3], (b * h, n, n))

        def f_kernel(bias):
            out = ops_attn.fused_attention(q, k, v, bias, heads=h,
                                           bias_repeat=rep, interpret=True)
            return jnp.sum(out * out)

        def f_ref(bias):
            out = ops_attn.attention_reference(q, k, v, bias, heads=h,
                                               bias_repeat=rep)
            return jnp.sum(out * out)

        np.testing.assert_allclose(
            np.asarray(jax.grad(f_kernel)(bias)),
            np.asarray(jax.grad(f_ref)(bias)), rtol=1e-4, atol=1e-5)

    def test_degenerate_tiles_fall_back(self):
        """Nq/Nk < 8 (e.g. 1x1 init-coverage pair maps) route to the XLA
        reference — Mosaic refuses those dots on-chip (r05)."""
        q = jnp.ones((4, 1, 16))
        k = jnp.ones((4, 1, 16))
        v = jnp.ones((4, 1, 16))
        # interpret=False on a CPU host: would fail inside pallas_call,
        # so passing proves the fallback took the XLA path
        out = ops_attn.fused_attention(q, k, v, interpret=False)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(
                ops_attn.attention_reference(q, k, v)), atol=1e-6)
