"""Child process for tests/test_multihost.py: one host of a 2-process
CPU cluster. argv: <process_id> <num_processes> <coordinator_addr>.

Must configure platform/device-count via env BEFORE importing jax, and
call multihost.initialize() before anything touches a backend — which is
the same contract a pod entrypoint has (multihost.py docstring); the
package import staying backend-free is load-bearing here (core/nerf.py
keeps its tables as numpy for exactly this reason).
"""

import os
import sys

pid, n, addr = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from alphafold2_tpu.parallel import multihost  # noqa: E402

multihost.initialize(addr, n, pid)

import jax  # noqa: E402
import numpy as np  # noqa: E402

assert jax.process_count() == n, jax.process_count()
assert jax.local_device_count() == 2
assert jax.device_count() == 2 * n

mesh = multihost.global_mesh(data=2 * n)

# each host contributes only its slice of the global batch
full = np.arange(16 * n, dtype=np.float32).reshape(2 * n, 8)
local = full[2 * pid:2 * pid + 2]
batch = multihost.host_local_batch_to_global({"x": local}, mesh)

glob = batch["x"]
assert glob.shape == (2 * n, 8)              # global logical shape
assert len(glob.addressable_shards) == 2     # but only local shards here

# the jitted sum reduces across hosts (cross-process collective over the
# data axis) — every process must see the full-array total
total = float(jax.jit(lambda t: t["x"].sum())(batch))
print(f"SUM {total}", flush=True)
