"""Speculative cascade + express lane tests (ISSUE 19): the confidence
gate units (pLDDT, distogram entropy, gate thresholds), CascadePolicy
validation and the draft-scheduler builder, the accept/escalate flow
end-to-end against stub executors, cross-tier cache isolation in BOTH
directions plus the keying tripwire, express featurization
byte-determinism and the FeaturePool express seams, the off-by-default
identity (scrubbed serve_stats + registry metric-name set), ProcFleet
config plumbing, and loadtest flag rot.

Scheduler-level tests run against stub executors choreographed by the
batch content (no model, no XLA), same pattern as tests/test_features:
the first token of a sequence decides its draft confidence, so one
suite exercises both gate outcomes deterministically.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from alphafold2_tpu import obs
from alphafold2_tpu.cache import FeatureCache, FoldCache
from alphafold2_tpu.data.featurize import tokenize
from alphafold2_tpu.obs.trace import NULL_TRACE
from alphafold2_tpu.serve import (BucketPolicy, CascadePolicy,
                                  ConfidenceGate, ConfidenceScore,
                                  FeaturePool, FoldRequest, FoldResponse,
                                  FoldTicket, RawFoldRequest, Scheduler,
                                  SchedulerConfig, ServeMetrics,
                                  StubEmbedder, build_draft_scheduler,
                                  distogram_entropy, express_featurize,
                                  plddt_score, score_response)

SEQ = "MKVLAARNDC"
MSA = ["MKVLAARNDC", "MKVLA-RNDC", "MKVRAARND-"]

# first-token choreography: the stub executor emits confidence HI for
# rows whose leading token clears HI_TOK, LO otherwise
HI_TOK = 5
HI, LO = 0.9, 0.2
HI_SEQ = np.full(10, 7, np.int32)     # draft folds confidently -> accept
LO_SEQ = np.full(10, 2, np.int32)     # draft is unsure -> escalate


class _TierStub:
    """Executor stand-in for one cascade tier: coords are a constant
    per-tier marker (so a response proves which tier produced it),
    confidence follows the first token, and the distogram head is
    optional — "sharp" (entropy ~ 0), "uniform" (entropy = 1), or
    absent, matching SchedulerConfig(confidence_summary) plumbing."""

    def __init__(self, marker, distogram=None):
        self.marker = float(marker)
        self.distogram = distogram
        self.runs = 0

    def run(self, batch, num_recycles, trace=NULL_TRACE, **kw):
        self.runs += 1
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        coords = np.full((b, n, 3), self.marker, np.float32)
        conf = np.where(seq[:, :1] >= HI_TOK, HI, LO)
        confidence = np.broadcast_to(conf, (b, n)).astype(np.float32).copy()

        class _R:
            pass

        res = _R()
        res.coords = coords
        res.confidence = confidence
        if self.distogram == "sharp":
            dg = np.zeros((b, n, n, 8), np.float32)
            dg[..., 0] = 50.0
            res.distogram = dg
        elif self.distogram == "uniform":
            res.distogram = np.zeros((b, n, n, 8), np.float32)
        return res

    def stats(self):
        return {"hits": 0, "misses": 0, "evictions": 0, "resident": 0,
                "max_entries": 1, "keys": []}


def _cascade_pair(gate=None, cache=None, draft_distogram=None,
                  flagship_kwargs=None, **policy_kwargs):
    """(flagship scheduler, draft scheduler, draft stub, flagship stub,
    flagship registry) wired the production way: shared FoldCache,
    distinct model_tags, isolated registries."""
    cache = FoldCache() if cache is None else cache
    draft_exec = _TierStub(1.0, distogram=draft_distogram)
    draft = build_draft_scheduler(
        draft_exec, BucketPolicy((16,)),
        config=SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                               num_recycles=0, confidence_summary=True),
        model_tag="draft", cache=cache)
    reg = obs.MetricsRegistry()
    flag_exec = _TierStub(2.0)
    sched = Scheduler(
        flag_exec, BucketPolicy((16,)),
        SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                        num_recycles=0,
                        **(flagship_kwargs or {})),
        ServeMetrics(registry=reg), cache=cache, model_tag="flagship",
        registry=reg,
        cascade=CascadePolicy(
            draft=draft,
            gate=gate or ConfidenceGate(accept_plddt=0.7),
            **policy_kwargs))
    return sched, draft, draft_exec, flag_exec, reg


@pytest.mark.quick
class TestConfidenceUnits:
    def test_plddt_mean_and_mask(self):
        conf = np.array([0.2, 0.4, 0.6, 0.8])
        assert plddt_score(conf) == pytest.approx(0.5)
        mask = np.array([0.0, 0.0, 1.0, 1.0])
        assert plddt_score(conf, mask) == pytest.approx(0.7)
        # batch shape works the same
        assert plddt_score(np.stack([conf, conf])) == pytest.approx(0.5)

    def test_plddt_validation(self):
        with pytest.raises(ValueError):
            plddt_score(np.zeros((0,)))
        with pytest.raises(ValueError):
            plddt_score(np.ones(4), mask=np.ones(3))
        with pytest.raises(ValueError):
            plddt_score(np.ones(4), mask=np.zeros(4))

    def test_distogram_entropy_extremes(self):
        sharp = np.zeros((3, 3, 8))
        sharp[..., 0] = 60.0
        assert distogram_entropy(sharp) == pytest.approx(0.0, abs=1e-6)
        # all-equal logits: exactly uniform, normalized entropy 1
        assert distogram_entropy(np.zeros((3, 3, 8))) == pytest.approx(1.0)

    def test_distogram_entropy_mask_and_validation(self):
        lg = np.zeros((2, 2, 8))
        lg[0, :, 0] = 60.0            # row 0 sharp, row 1 uniform
        mask = np.array([[1.0, 1.0], [0.0, 0.0]])
        assert distogram_entropy(lg, mask) == pytest.approx(0.0, abs=1e-6)
        with pytest.raises(ValueError):
            distogram_entropy(np.zeros((3, 3, 1)))   # <2 bins
        with pytest.raises(ValueError):
            distogram_entropy(lg, mask=np.ones((3, 3)))
        with pytest.raises(ValueError):
            distogram_entropy(lg, mask=np.zeros((2, 2)))

    def test_score_scalar(self):
        assert ConfidenceScore(plddt=0.8).score == pytest.approx(0.8)
        assert ConfidenceScore(plddt=0.8, entropy=0.25).score \
            == pytest.approx(0.6)

    def test_score_response(self):
        resp = FoldResponse(request_id="r", status="ok",
                            confidence=np.array([0.6, 0.8]),
                            distogram_entropy=0.5)
        s = score_response(resp)
        assert s.plddt == pytest.approx(0.7)
        assert s.entropy == pytest.approx(0.5)
        with pytest.raises(ValueError):
            score_response(FoldResponse(request_id="r", status="ok"))

    def test_gate_thresholds(self):
        gate = ConfidenceGate(accept_plddt=0.7)
        assert gate.accepts(ConfidenceScore(plddt=0.71))
        assert not gate.accepts(ConfidenceScore(plddt=0.69))
        # entropy ceiling only consulted when the score carries one
        gate = ConfidenceGate(accept_plddt=0.5, max_entropy=0.4)
        assert gate.accepts(ConfidenceScore(plddt=0.9))
        assert gate.accepts(ConfidenceScore(plddt=0.9, entropy=0.3))
        assert not gate.accepts(ConfidenceScore(plddt=0.9, entropy=0.5))

    def test_gate_validation(self):
        with pytest.raises(ValueError):
            ConfidenceGate(accept_plddt=1.5)
        with pytest.raises(ValueError):
            ConfidenceGate(max_entropy=-0.1)


@pytest.mark.quick
class TestCascadePolicy:
    def test_draft_shape_required(self):
        with pytest.raises(ValueError):
            CascadePolicy(draft=None)
        with pytest.raises(ValueError):
            CascadePolicy(draft=object())      # no .submit

        class _SubmitOnly:
            def submit(self, request):
                raise NotImplementedError

        with pytest.raises(ValueError):
            CascadePolicy(draft=_SubmitOnly())  # no .model_tag

    def test_knob_bounds(self):
        class _Draft:
            model_tag = "draft"

            def submit(self, request):
                raise NotImplementedError

        with pytest.raises(ValueError):
            CascadePolicy(draft=_Draft(), escalation_priority=-1)
        with pytest.raises(ValueError):
            CascadePolicy(draft=_Draft(), draft_deadline_s=0.0)

    def test_draft_deadline_combinations(self):
        class _Draft:
            model_tag = "draft"

            def submit(self, request):
                raise NotImplementedError

        uncapped = CascadePolicy(draft=_Draft())
        assert uncapped.draft_deadline(None) is None
        assert uncapped.draft_deadline(5.0) == pytest.approx(5.0)
        capped = CascadePolicy(draft=_Draft(), draft_deadline_s=2.0)
        assert capped.draft_deadline(None) == pytest.approx(2.0)
        assert capped.draft_deadline(5.0) == pytest.approx(2.0)
        assert capped.draft_deadline(1.0) == pytest.approx(1.0)

    def test_attach_rejects_tag_collision(self):
        cache = FoldCache()
        draft = build_draft_scheduler(
            _TierStub(1.0), BucketPolicy((16,)),
            config=SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                                   num_recycles=0),
            model_tag="same-tag", cache=cache)
        reg = obs.MetricsRegistry()
        with pytest.raises(ValueError):
            Scheduler(_TierStub(2.0), BucketPolicy((16,)),
                      SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                                      num_recycles=0),
                      ServeMetrics(registry=reg), cache=cache,
                      model_tag="same-tag", registry=reg,
                      cascade=CascadePolicy(draft=draft))

    def test_builder_isolates_registry_and_forces_summary(self):
        before = len(obs.get_registry().metrics())
        draft = build_draft_scheduler(_TierStub(1.0), BucketPolicy((16,)))
        # nothing minted into the global registry; the draft carries its
        # own (ServeMetrics mirrors dedup by NAME — a shared registry
        # would silently sum draft and flagship series)
        assert len(obs.get_registry().metrics()) == before
        assert draft._registry is not obs.get_registry()
        assert len(draft._registry.metrics()) > 0
        # default config folds the distogram summary in for the gate
        assert draft.config.confidence_summary is True
        assert draft.model_tag == "draft"


class TestCascadeFlow:
    def test_confident_draft_accepted(self):
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair()
        with sched:
            resp = sched.submit(FoldRequest(seq=HI_SEQ)).result(timeout=30)
        assert resp.ok
        assert resp.tier == "draft"
        assert resp.escalated is False
        assert float(resp.coords[0, 0]) == pytest.approx(1.0)
        assert resp.confidence_score == pytest.approx(HI, abs=1e-6)
        assert draft_exec.runs == 1
        assert flag_exec.runs == 0
        snap = sched.serve_stats()
        casc = snap["cascade"]
        assert casc["draft_tag"] == "draft"
        assert casc["draft_accepted"] == 1
        assert casc["escalated"] == 0
        assert casc["cross_tier_hits"] == 0
        assert casc["accept_rate"] == pytest.approx(1.0)
        assert casc["mean_confidence"] == pytest.approx(HI, abs=1e-6)
        assert casc["draft"]["served"] == 1
        # an accepted draft still counts as flagship-side served work
        assert snap["served"] == 1

    def test_unsure_draft_escalates(self):
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair()
        with sched:
            resp = sched.submit(FoldRequest(seq=LO_SEQ)).result(timeout=30)
        assert resp.ok
        assert resp.tier == "flagship"
        assert resp.escalated is True
        assert float(resp.coords[0, 0]) == pytest.approx(2.0)
        assert resp.confidence_score == pytest.approx(LO, abs=1e-6)
        assert draft_exec.runs == 1
        assert flag_exec.runs == 1
        casc = sched.serve_stats()["cascade"]
        assert casc["draft_accepted"] == 0
        assert casc["escalated"] == 1
        assert casc["accept_rate"] == pytest.approx(0.0)

    def test_entropy_ceiling_escalates_confident_plddt(self):
        """A pointwise-confident but globally undecided draft (uniform
        distogram, entropy 1.0) must escalate under an entropy gate."""
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair(
            gate=ConfidenceGate(accept_plddt=0.5, max_entropy=0.5),
            draft_distogram="uniform")
        with sched:
            resp = sched.submit(FoldRequest(seq=HI_SEQ)).result(timeout=30)
        assert resp.tier == "flagship" and resp.escalated
        # score = plddt * (1 - entropy) = 0.9 * 0 = 0
        assert resp.confidence_score == pytest.approx(0.0, abs=1e-6)
        assert flag_exec.runs == 1

    def test_sharp_distogram_accepted_with_entropy_on_response(self):
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair(
            gate=ConfidenceGate(accept_plddt=0.5, max_entropy=0.5),
            draft_distogram="sharp")
        with sched:
            resp = sched.submit(FoldRequest(seq=HI_SEQ)).result(timeout=30)
        assert resp.tier == "draft"
        assert resp.distogram_entropy == pytest.approx(0.0, abs=1e-6)
        assert resp.confidence_score == pytest.approx(HI, abs=1e-4)
        assert flag_exec.runs == 0

    def test_refusing_draft_fails_over_to_flagship(self):
        """An unstarted draft refuses every submit; the caller must
        still get a flagship fold — the failed speculation costs them
        nothing but the attempt."""
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair(
            manage_draft=False)       # flagship start() leaves draft down
        with sched:
            resp = sched.submit(FoldRequest(seq=HI_SEQ)).result(timeout=30)
        assert resp.ok
        assert resp.tier == "flagship" and resp.escalated
        assert float(resp.coords[0, 0]) == pytest.approx(2.0)
        assert draft_exec.runs == 0
        casc = sched.serve_stats()["cascade"]
        assert casc["draft_errors"] == 1
        assert casc["escalated"] == 1

    def test_bulk_never_cascades(self):
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair()
        with sched:
            resp = sched.submit(
                FoldRequest(seq=HI_SEQ, qos="bulk")).result(timeout=30)
        assert resp.ok
        assert resp.tier == ""            # plain flagship path
        assert float(resp.coords[0, 0]) == pytest.approx(2.0)
        assert draft_exec.runs == 0
        assert sched.serve_stats()["cascade"]["draft_accepted"] == 0

    def test_express_cascades_and_mints_lazy_metrics(self):
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair()
        names = {m.name for m in reg.metrics()}
        assert "serve_cascade_requests_total" in names     # armed at attach
        assert "serve_express_requests_total" not in names  # lazy
        with sched:
            assert "express" not in sched.serve_stats()
            resp = sched.submit(
                FoldRequest(seq=HI_SEQ, qos="express")).result(timeout=30)
        assert resp.ok and resp.tier == "draft"
        assert sched.serve_stats()["express"] == {"served": 1}
        names = {m.name for m in reg.metrics()}
        assert "serve_express_requests_total" in names
        assert "serve_express_latency_seconds" in names


class TestCrossTierIsolation:
    def test_accepted_draft_caches_under_draft_key_only(self):
        cache = FoldCache()
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair(
            cache=cache)
        req = FoldRequest(seq=HI_SEQ)
        with sched:
            assert sched.submit(req).result(timeout=30).tier == "draft"
            draft_key = draft._cache_key_for(req)
            flagship_key = sched._cache_key_for(req)
            assert draft_key != flagship_key
            assert cache.get(draft_key) is not None
            assert cache.get(flagship_key) is None
            # a repeat serves from the DRAFT's cache tier: zero new
            # executions on either tier, still labelled draft
            resp2 = sched.submit(FoldRequest(seq=HI_SEQ)).result(timeout=30)
        assert resp2.tier == "draft" and resp2.source == "cache"
        assert draft_exec.runs == 1
        assert flag_exec.runs == 0

    def test_flagship_store_hit_short_circuits_draft(self):
        cache = FoldCache()
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair(
            cache=cache)
        with sched:
            first = sched.submit(FoldRequest(seq=LO_SEQ)).result(timeout=30)
            assert first.escalated and flag_exec.runs == 1
            draft_runs = draft_exec.runs
            # the flagship result is cached now; a repeat must NOT
            # speculate a draft fold on top of a free full-quality hit
            resp = sched.submit(FoldRequest(seq=LO_SEQ)).result(timeout=30)
        assert resp.tier == "flagship" and resp.source == "cache"
        assert resp.escalated is False
        assert float(resp.coords[0, 0]) == pytest.approx(2.0)
        assert draft_exec.runs == draft_runs
        assert flag_exec.runs == 1

    def test_cross_tier_keying_tripwire(self):
        """Force the keying regression the tripwire exists for: equal
        draft/flagship cache keys must never speculate — straight to
        the flagship, counted in the pinned counter."""
        sched, draft, draft_exec, flag_exec, reg = _cascade_pair()
        draft.model_tag = "flagship"      # simulate the regression
        with sched:
            resp = sched.submit(FoldRequest(seq=HI_SEQ)).result(timeout=30)
        assert resp.ok
        assert resp.tier == "flagship" and resp.escalated
        assert draft_exec.runs == 0       # never speculated across it
        assert sched.serve_stats()["cascade"]["cross_tier_hits"] == 1
        assert reg.counter(
            "serve_cascade_cross_tier_hits_total").value() == 1


@pytest.mark.quick
class TestExpressFeaturizer:
    def test_byte_determinism(self):
        emb = StubEmbedder()
        f1 = express_featurize(RawFoldRequest(SEQ, qos="express"), emb)
        f2 = express_featurize(RawFoldRequest(SEQ, qos="express"),
                               StubEmbedder())
        assert f1.seq.tobytes() == f2.seq.tobytes()
        assert f1.msa.tobytes() == f2.msa.tobytes()
        # two rows, query first (bucketing convention)
        assert f1.msa.shape == (2, len(SEQ))
        assert np.array_equal(f1.msa[0], f1.seq)

    def test_raw_msa_ignored_by_design(self):
        emb = StubEmbedder()
        with_msa = express_featurize(
            RawFoldRequest(SEQ, msa=MSA, qos="express"), emb)
        without = express_featurize(RawFoldRequest(SEQ, qos="express"), emb)
        assert with_msa.msa.tobytes() == without.msa.tobytes()

    def test_embedder_digest_namespaces(self):
        assert StubEmbedder(16).digest == "stub-embedder-v1-d16"
        assert StubEmbedder(16).digest != StubEmbedder(8).digest
        pool = FeaturePool(workers=1, express=StubEmbedder(),
                           registry=obs.MetricsRegistry())
        express_digest = pool._digest_for(
            RawFoldRequest(SEQ, qos="express"))
        assert express_digest.startswith("express:")
        assert express_digest != pool.config_digest
        # online jobs key under the featurizer's digest, untouched
        assert pool._digest_for(RawFoldRequest(SEQ)) == pool.config_digest

    def test_qos_validation(self):
        with pytest.raises(ValueError):
            RawFoldRequest(SEQ, qos="turbo")
        with pytest.raises(ValueError):
            FoldRequest(seq=tokenize(SEQ), qos="turbo")
        with pytest.raises(ValueError):
            FeaturePool(workers=1, express_deadline_s=0.0)

    def test_express_without_embedder_errors_loudly(self):
        pool = FeaturePool(workers=1, registry=obs.MetricsRegistry())
        sink = _SinkScheduler()
        ticket = pool.submit_raw(RawFoldRequest(SEQ, qos="express"), sink)
        resp = ticket.result(timeout=10)
        assert resp.status == "error"
        assert "express" in resp.error
        assert sink.requests == []        # never reached the fold tier
        pool.stop()

    def test_express_bypasses_featurize_fn(self):
        """The online featurizer (MSA prep) must never run for an
        express job — that is the lane's whole point."""
        def boom(raw):
            raise AssertionError("online featurizer ran for express")

        pool = FeaturePool(workers=1, featurize_fn=boom,
                           config_digest="boom-cfg",
                           express=StubEmbedder(),
                           express_deadline_s=30.0,
                           registry=obs.MetricsRegistry())
        sink = _SinkScheduler()
        resp = pool.submit_raw(
            RawFoldRequest(SEQ, qos="express"), sink).result(timeout=10)
        assert resp.ok
        assert len(sink.requests) == 1
        req = sink.requests[0]
        assert req.qos == "express"
        assert req.msa.shape == (2, len(SEQ))
        # express fold deadline capped by the lane's promise
        assert req.deadline_s is not None and req.deadline_s <= 30.0
        # the online path still runs (and here, fails through) boom
        online = pool.submit_raw(RawFoldRequest(SEQ), sink).result(
            timeout=10)
        assert online.status == "error"
        pool.stop()

    def test_express_end_to_end_and_feature_cache_namespace(self):
        """Express raw jobs fold for real on a scheduler, and their
        cached features live under the embedder's namespace — an online
        job for the same sequence must featurize separately."""
        reg = obs.MetricsRegistry()
        fcache = FeatureCache(registry=reg)
        pool = FeaturePool(workers=1, cache=fcache,
                           express=StubEmbedder(), registry=reg)
        sched = Scheduler(_TierStub(3.0), BucketPolicy((16,)),
                          SchedulerConfig(max_batch_size=2,
                                          max_wait_ms=5.0,
                                          num_recycles=0),
                          ServeMetrics(registry=reg), registry=reg)
        with sched:
            ex1 = pool.submit_raw(
                RawFoldRequest(SEQ, qos="express"), sched).result(
                    timeout=30)
            ex2 = pool.submit_raw(
                RawFoldRequest(SEQ, qos="express"), sched).result(
                    timeout=30)
            online = pool.submit_raw(
                RawFoldRequest(SEQ), sched).result(timeout=30)
        pool.stop()
        assert ex1.ok and ex2.ok and online.ok
        snap = pool.snapshot()
        # the express repeat hit the feature cache; the online job for
        # the SAME sequence missed it (distinct key namespace)
        assert snap["cache_hits"] == 1
        assert snap["executions"] == 2


class _SinkScheduler:
    """Fold-scheduler stand-in for FeaturePool seam tests: records the
    FoldRequests it is handed and resolves them immediately."""

    def __init__(self):
        self.tracer = obs.Tracer()
        self.requests = []

    def submit(self, request, trace=None):
        self.requests.append(request)
        ticket = FoldTicket(request.request_id)
        ticket._resolve(FoldResponse(request_id=request.request_id,
                                     status="ok"))
        return ticket


class TestOffByDefault:
    def _run_one(self, pass_kwarg):
        reg = obs.MetricsRegistry()
        kwargs = {"cascade": None} if pass_kwarg else {}
        sched = Scheduler(_TierStub(2.0), BucketPolicy((16,)),
                          SchedulerConfig(max_batch_size=2,
                                          max_wait_ms=5.0,
                                          num_recycles=0),
                          ServeMetrics(registry=reg), registry=reg,
                          **kwargs)
        with sched:
            for seq in (HI_SEQ, LO_SEQ, HI_SEQ[:8]):
                resp = sched.submit(FoldRequest(seq=seq)).result(timeout=30)
                assert resp.ok and resp.tier == "" \
                    and resp.escalated is False
        return sched.serve_stats(), {m.name for m in reg.metrics()}

    def test_scrubbed_stats_and_metric_name_identity(self):
        """The off switch: cascade=None (the default) must leave both
        serve_stats() and the registry metric-name set byte-identical
        to a scheduler built without the kwarg at all, with no cascade/
        express surface anywhere."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        stats_a, names_a = self._run_one(pass_kwarg=True)
        stats_b, names_b = self._run_one(pass_kwarg=False)
        assert json.dumps(scrub(stats_a), sort_keys=True, default=str) \
            == json.dumps(scrub(stats_b), sort_keys=True, default=str)
        assert names_a == names_b
        for stats in (stats_a, stats_b):
            assert "cascade" not in stats
            assert "express" not in stats
        for names in (names_a, names_b):
            assert not any(n.startswith("serve_cascade_") for n in names)
            assert not any(n.startswith("serve_express_") for n in names)


class TestProcFleetPlumbing:
    def test_cascade_knob_round_trips_to_replica_configs(self, tmp_path):
        from alphafold2_tpu.fleet.procfleet import ProcFleet
        casc = {"model": {"dim": 16, "depth": 1}, "accept_plddt": 0.8,
                "max_entropy": 0.9, "escalation_priority": 5,
                "draft_deadline_s": 2.0}
        fleet = ProcFleet(2, str(tmp_path / "run"), cascade=casc)
        assert len(fleet.replicas) == 2
        for handle in fleet.replicas:
            with open(handle.config_path) as fh:
                cfg = json.load(fh)
            assert cfg["cascade"] == casc

    def test_cascade_defaults_off(self, tmp_path):
        from alphafold2_tpu.fleet.procfleet import ProcFleet
        fleet = ProcFleet(1, str(tmp_path / "run"))
        with open(fleet.replicas[0].config_path) as fh:
            cfg = json.load(fh)
        assert cfg["cascade"] is None


class TestLoadtestFlags:
    """Flag-rot guard: the documented --cascade/--draft-accept-rate/
    --express-rate knobs must parse, run, and report (same pattern as
    the continuous/bulk loadtest flag tests)."""

    def _main(self):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        import serve_loadtest
        return serve_loadtest.main

    def test_cascade_rejects_multi_process_modes(self, capsys):
        main = self._main()
        assert main(["--cascade", "--procs", "2"]) == 2
        assert main(["--express-rate", "0.5", "--replicas", "2"]) == 2

    def test_cascade_and_express_report(self, capsys):
        main = self._main()
        rc = main(["--requests", "6", "--lengths", "12",
                   "--buckets", "16", "--msa-depth", "2",
                   "--max-batch", "2", "--concurrency", "2",
                   "--num-recycles", "0", "--dim", "32", "--depth", "1",
                   "--cache", "on", "--cascade",
                   "--draft-accept-rate", "0.5",
                   "--express-rate", "0.34",
                   "--metrics-path", "/tmp/test_cascade_loadtest.jsonl"])
        assert rc == 0
        report = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        casc = report["cascade"]
        assert casc["scripted_gate"] is True
        assert casc["draft_accepted"] + casc["escalated"] > 0
        assert casc["cross_tier_hits"] == 0
        assert casc["flagship_folds"] <= report["served"]
        assert casc["accel_seconds"]["total"] > 0
        assert set(report["latency_by_tier"]) == {"draft", "flagship"}
        assert report["express"].get("served", 0) > 0
        assert "express" in report["latency_by_lane"]
