"""Equivariant-refiner tests: E(3) equivariance properties (the reference
has no such tests — its equivariant modules are external packages), plus
the README-era structure_module_type model configs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.core import quaternion as quat
from alphafold2_tpu.model.refiners import EGNNLayer, EnAttentionLayer, Refiner


def rotation(key):
    q = jax.random.normal(key, (4,))
    return quat.quaternion_to_matrix(q / jnp.linalg.norm(q))


def make_inputs(key, b=1, n=10, d=16):
    k1, k2 = jax.random.split(key)
    h = jax.random.normal(k1, (b, n, d))
    x = jax.random.normal(k2, (b, n, 3)) * 3
    mask = jnp.ones((b, n), dtype=bool)
    return h, x, mask


@pytest.mark.parametrize("layer_cls", [EGNNLayer, EnAttentionLayer])
def test_equivariance(layer_cls):
    h, x, mask = make_inputs(jax.random.PRNGKey(0))
    layer = layer_cls(dim=16)
    params = layer.init(jax.random.PRNGKey(1), h, x, mask=mask)

    # break the zero-init so the coordinate update is non-trivial
    params = jax.tree.map(
        lambda t: t + 0.1 * jax.random.normal(jax.random.PRNGKey(2),
                                              t.shape), params)

    rot = rotation(jax.random.PRNGKey(3))
    trans = jnp.asarray([1.0, -2.0, 0.5])

    h1, x1 = layer.apply(params, h, x, mask=mask)
    h2, x2 = layer.apply(params, h, x @ rot + trans, mask=mask)

    # invariant features identical; coordinates transform with the input
    assert np.allclose(h1, h2, atol=1e-4)
    assert np.allclose(x1 @ rot + trans, x2, atol=1e-4)
    # update is genuinely non-trivial
    assert float(jnp.abs(x1 - x).max()) > 1e-4


def test_refiner_mask_keeps_padding_effectless():
    h, x, _ = make_inputs(jax.random.PRNGKey(4), n=12)
    mask = jnp.ones((1, 12), dtype=bool).at[:, 8:].set(False)
    ref = Refiner(dim=16, kind="egnn", iters=2)
    params = ref.init(jax.random.PRNGKey(5), h, x, mask=mask)
    # perturb params so the zero-initialized coordinate update is live —
    # otherwise the coordinate assertion is vacuous
    params = jax.tree.map(
        lambda t: t + 0.1 * jax.random.normal(jax.random.PRNGKey(6),
                                              t.shape), params)
    h1, x1 = ref.apply(params, h, x, mask=mask)
    # corrupt padded nodes: valid outputs unchanged
    h_c = h.at[:, 8:].add(100.0)
    x_c = x.at[:, 8:].add(50.0)
    h2, x2 = ref.apply(params, h_c, x_c, mask=mask)
    assert np.allclose(h1[:, :8], h2[:, :8], atol=1e-4)
    assert np.allclose(x1[:, :8], x2[:, :8], atol=1e-3)


@pytest.mark.parametrize("kind", ["egnn", "en", "se3"])
def test_model_with_refiner_structure_module(kind):
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_type=kind,
                       structure_module_depth=2)
    seq = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 21)
    params = model.init(jax.random.PRNGKey(1), seq)
    coords = model.apply(params, seq)
    assert coords.shape == (1, 8, 3)
    assert bool(jnp.isfinite(coords).all())


def test_model_ipa_plus_refinement_iters():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1,
                       structure_module_refinement_iters=2)
    seq = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, 21)
    params = model.init(jax.random.PRNGKey(3), seq)
    coords, conf = model.apply(params, seq, return_confidence=True)
    assert coords.shape == (1, 8, 3)
    assert conf.shape == (1, 8, 1)


def test_refiner_structure_module_backward():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_type="egnn",
                       structure_module_depth=1)
    seq = jax.random.randint(jax.random.PRNGKey(4), (1, 6), 0, 21)
    params = model.init(jax.random.PRNGKey(5), seq)

    def loss(p):
        return jnp.sum(model.apply(p, seq) ** 2)

    g = jax.grad(loss)(params)
    assert all(bool(jnp.isfinite(t).all()) for t in jax.tree.leaves(g))


def test_seq_and_msa_embed_projection():
    # pretrained-LM embeds at num_embedds dim get projected in-model
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, num_embedds=48)
    seq = jax.random.randint(jax.random.PRNGKey(6), (1, 8), 0, 21)
    msa = jax.random.randint(jax.random.PRNGKey(7), (1, 3, 8), 0, 21)
    params = model.init(jax.random.PRNGKey(8), seq, msa=msa)
    ret = model.apply(
        params, seq, msa=msa,
        seq_embed=jnp.ones((1, 8, 48)),
        msa_embed=jnp.ones((1, 3, 8, 48)))
    assert ret.distance.shape == (1, 8, 8, 37)
