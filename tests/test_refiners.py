"""Equivariant-refiner tests: E(3) equivariance properties (the reference
has no such tests — its equivariant modules are external packages), plus
the README-era structure_module_type model configs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.core import quaternion as quat
from alphafold2_tpu.model.refiners import EGNNLayer, EnAttentionLayer, Refiner


def rotation(key):
    q = jax.random.normal(key, (4,))
    return quat.quaternion_to_matrix(q / jnp.linalg.norm(q))


def make_inputs(key, b=1, n=10, d=16):
    k1, k2 = jax.random.split(key)
    h = jax.random.normal(k1, (b, n, d))
    x = jax.random.normal(k2, (b, n, 3)) * 3
    mask = jnp.ones((b, n), dtype=bool)
    return h, x, mask


@pytest.mark.parametrize("layer_cls", [EGNNLayer, EnAttentionLayer])
def test_equivariance(layer_cls):
    h, x, mask = make_inputs(jax.random.PRNGKey(0))
    layer = layer_cls(dim=16)
    params = layer.init(jax.random.PRNGKey(1), h, x, mask=mask)

    # break the zero-init so the coordinate update is non-trivial
    params = jax.tree.map(
        lambda t: t + 0.1 * jax.random.normal(jax.random.PRNGKey(2),
                                              t.shape), params)

    rot = rotation(jax.random.PRNGKey(3))
    trans = jnp.asarray([1.0, -2.0, 0.5])

    h1, x1 = layer.apply(params, h, x, mask=mask)
    h2, x2 = layer.apply(params, h, x @ rot + trans, mask=mask)

    # invariant features identical; coordinates transform with the input
    assert np.allclose(h1, h2, atol=1e-4)
    assert np.allclose(x1 @ rot + trans, x2, atol=1e-4)
    # update is genuinely non-trivial
    assert float(jnp.abs(x1 - x).max()) > 1e-4


def test_refiner_mask_keeps_padding_effectless():
    h, x, _ = make_inputs(jax.random.PRNGKey(4), n=12)
    mask = jnp.ones((1, 12), dtype=bool).at[:, 8:].set(False)
    ref = Refiner(dim=16, kind="egnn", iters=2)
    params = ref.init(jax.random.PRNGKey(5), h, x, mask=mask)
    # perturb params so the zero-initialized coordinate update is live —
    # otherwise the coordinate assertion is vacuous
    params = jax.tree.map(
        lambda t: t + 0.1 * jax.random.normal(jax.random.PRNGKey(6),
                                              t.shape), params)
    h1, x1 = ref.apply(params, h, x, mask=mask)
    # corrupt padded nodes: valid outputs unchanged
    h_c = h.at[:, 8:].add(100.0)
    x_c = x.at[:, 8:].add(50.0)
    h2, x2 = ref.apply(params, h_c, x_c, mask=mask)
    assert np.allclose(h1[:, :8], h2[:, :8], atol=1e-4)
    assert np.allclose(x1[:, :8], x2[:, :8], atol=1e-3)


@pytest.mark.parametrize("kind", ["egnn", "en", "se3"])
def test_model_with_refiner_structure_module(kind):
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_type=kind,
                       structure_module_depth=2)
    seq = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 21)
    params = model.init(jax.random.PRNGKey(1), seq)
    coords = model.apply(params, seq)
    assert coords.shape == (1, 8, 3)
    assert bool(jnp.isfinite(coords).all())


def test_model_ipa_plus_refinement_iters():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1,
                       structure_module_refinement_iters=2)
    seq = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, 21)
    params = model.init(jax.random.PRNGKey(3), seq)
    coords, conf = model.apply(params, seq, return_confidence=True)
    assert coords.shape == (1, 8, 3)
    assert conf.shape == (1, 8, 1)


def test_refiner_structure_module_backward():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_type="egnn",
                       structure_module_depth=1)
    seq = jax.random.randint(jax.random.PRNGKey(4), (1, 6), 0, 21)
    params = model.init(jax.random.PRNGKey(5), seq)

    def loss(p):
        return jnp.sum(model.apply(p, seq) ** 2)

    g = jax.grad(loss)(params)
    assert all(bool(jnp.isfinite(t).all()) for t in jax.tree.leaves(g))


def test_seq_and_msa_embed_projection():
    # pretrained-LM embeds at num_embedds dim get projected in-model
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, num_embedds=48)
    seq = jax.random.randint(jax.random.PRNGKey(6), (1, 8), 0, 21)
    msa = jax.random.randint(jax.random.PRNGKey(7), (1, 3, 8), 0, 21)
    params = model.init(jax.random.PRNGKey(8), seq, msa=msa)
    ret = model.apply(
        params, seq, msa=msa,
        seq_embed=jnp.ones((1, 8, 48)),
        msa_embed=jnp.ones((1, 3, 8, 48)))
    assert ret.distance.shape == (1, 8, 8, 37)


# ---------------------------------------------------------------------------
# Atom-level EGNN refinement (round-4 VERDICT #8; notebook cells 25-33)
# ---------------------------------------------------------------------------


class TestAtomEGNNRefiner:
    def _inputs(self, key, b=1, l=6, d=16):
        ks = jax.random.split(key, 3)
        h = jax.random.normal(ks[0], (b, l, d))
        ca = jnp.cumsum(
            jax.random.normal(ks[1], (b, l, 3)) * 0.5 +
            jnp.asarray([3.8, 0.0, 0.0]), axis=1)
        seq = jax.random.randint(ks[2], (b, l), 0, 20)
        mask = jnp.ones((b, l), bool)
        return h, ca, seq, mask

    def test_shapes_and_finite(self):
        from alphafold2_tpu.model.refiners import AtomEGNNRefiner

        h, ca, seq, mask = self._inputs(jax.random.PRNGKey(0))
        ref = AtomEGNNRefiner(dim=16, iters=2)
        params = ref.init(jax.random.PRNGKey(1), h, ca, seq, mask=mask)
        h_at, atoms = ref.apply(params, h, ca, seq, mask=mask)
        assert atoms.shape == (1, 6, 14, 3)
        assert h_at.shape == (1, 6, 14, 16)
        assert np.isfinite(np.asarray(atoms)).all()
        # masked atom slots (per-AA cloud mask) stay zeroed
        from alphafold2_tpu.data.scn import scn_cloud_mask
        cloud = np.asarray(scn_cloud_mask(seq))
        assert np.abs(np.asarray(atoms)[cloud == 0]).max() == 0.0

    def test_equivariance(self):
        """Rotate+translate the CA trace -> the refined atom cloud
        rotates/translates identically (E(3) equivariance through the
        scaffold build-out AND the sparse message passing)."""
        from alphafold2_tpu.model.refiners import AtomEGNNRefiner
        from alphafold2_tpu.data.scn import scn_cloud_mask

        h, ca, seq, mask = self._inputs(jax.random.PRNGKey(2))
        R = rotation(jax.random.PRNGKey(3))
        t = jnp.asarray([1.5, -2.0, 0.5])

        ref = AtomEGNNRefiner(dim=16, iters=2)
        params = ref.init(jax.random.PRNGKey(4), h, ca, seq, mask=mask)
        _, atoms = ref.apply(params, h, ca, seq, mask=mask)
        _, atoms_rt = ref.apply(params, h, ca @ R.T + t, seq, mask=mask)
        cloud = np.asarray(scn_cloud_mask(seq))[..., None]
        expect = (np.asarray(atoms) @ np.asarray(R).T +
                  np.asarray(t)) * cloud
        np.testing.assert_allclose(np.asarray(atoms_rt), expect,
                                   rtol=1e-4, atol=2e-4)

    def test_covalent_graph_is_the_message_path(self):
        """Zeroed bond mask (max_degree slots of a disconnected graph)
        must leave coordinates at the scaffold: messages ride ONLY the
        covalent adjacency."""
        from alphafold2_tpu.core.nerf import sidechain_container
        from alphafold2_tpu.model.refiners import SparseEGNNLayer

        b, n, d, k = 1, 8, 8, 4
        key = jax.random.PRNGKey(5)
        h = jax.random.normal(key, (b, n, d))
        x = jax.random.normal(key, (b, n, 3))
        idx = jnp.zeros((b, n, k), jnp.int32)
        dead = jnp.zeros((b, n, k))
        layer = SparseEGNNLayer(dim=d, max_degree=k)
        params = layer.init(jax.random.PRNGKey(6), h, x, idx, dead)
        _, x_out = layer.apply(params, h, x, idx, dead)
        np.testing.assert_allclose(np.asarray(x_out), np.asarray(x),
                                   atol=1e-6)

    def test_model_decode_path(self):
        """Full model decode with structure_module_refinement='egnn-atom':
        coords stay (b, n, 3) CA, ReturnValues.atoms carries the 14-slot
        cloud, gradients flow."""
        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=8,
                           predict_coords=True, structure_module_depth=1,
                           structure_module_refinement_iters=2,
                           structure_module_refinement="egnn-atom")
        seq = jax.random.randint(jax.random.PRNGKey(7), (1, 8), 0, 20)
        msa = seq[:, None]
        mask = jnp.ones((1, 8), bool)
        params = model.init(jax.random.PRNGKey(8), seq, msa=msa,
                            mask=mask, msa_mask=mask[:, None])
        coords, ret = model.apply(params, seq, msa=msa, mask=mask,
                                  msa_mask=mask[:, None],
                                  return_aux_logits=True)
        assert coords.shape == (1, 8, 3)
        assert ret.atoms.shape == (1, 8, 14, 3)
        np.testing.assert_allclose(np.asarray(coords),
                                   np.asarray(ret.atoms[:, :, 1]))

        def loss(p):
            c, _ = model.apply(p, seq, msa=msa, mask=mask,
                               msa_mask=mask[:, None],
                               return_aux_logits=True)
            return jnp.sum(c * c)

        g = jax.grad(loss)(params)
        leaves = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
        assert any(float(jnp.abs(x).max()) > 0 for x in leaves)
