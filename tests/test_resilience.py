"""Failure-domain hardening tests (ISSUE 5): transient retry with
backoff, poison isolation by batch bisection + keyed quarantine,
non-finite output validation, the executor watchdog + rebuild, the
degraded-mode circuit breaker, fault-plan determinism, peer markdown
recovery, and the seeded chaos end-to-end acceptance run.

All scheduler tests run against scripted stub executors (no JAX
compile) so the failure SCHEDULING is what's under test; the real
FoldExecutor's fault hooks are covered by the chaos phase of
tools/serve_smoke.sh and its warmup/AOT paths by test_serve.py.
"""

import math
import threading
import time

import numpy as np
import pytest

from alphafold2_tpu.cache import FoldCache
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, FaultInjected, FaultPlan,
                                  FoldRequest, FoldTicket, RetryPolicy,
                                  Scheduler, SchedulerConfig, ServeMetrics,
                                  TransientExecutorError)
from alphafold2_tpu.serve.resilience import (CircuitBreaker, Quarantine,
                                             WatchdogTimeout,
                                             run_with_watchdog)


def seq_of(n=8, base=0):
    return (np.arange(n, dtype=np.int32) + base) % 20


class StubExecutor:
    """Scripted executor: `behave(batch, call_index)` may raise, sleep,
    or return "nan" to corrupt row 0; otherwise finite coords."""

    def __init__(self, behave=None, faults=None):
        self.calls = 0
        self.behave = behave or (lambda batch, call: None)
        self.faults = faults

    def run(self, batch, num_recycles, trace=None):
        self.calls += 1
        if self.faults is not None:
            self.faults.on_executor_run(batch)
        out = self.behave(batch, self.calls)
        b, n = batch["seq"].shape
        coords = np.ones((b, n, 3), np.float32)
        confidence = np.full((b, n), 0.5, np.float32)
        if out == "nan":
            coords[0] = np.nan
        class R:                                   # noqa: E306
            pass
        R.coords, R.confidence = coords, confidence
        return R()

    def stats(self):
        return {"calls": self.calls}


def make_scheduler(executor, retry, max_batch=2, max_wait_ms=10.0,
                   cache=None, **kw):
    return Scheduler(
        executor, BucketPolicy((16,)),
        SchedulerConfig(max_batch_size=max_batch, max_wait_ms=max_wait_ms,
                        msa_depth=0, poll_ms=2.0),
        cache=cache, model_tag="resil", retry=retry,
        registry=MetricsRegistry(), **kw)


def row_matches(batch, seq):
    """True when any real batch row equals `seq` (poison detection the
    way a content-addressed failure would follow the request)."""
    seqs, mask = np.asarray(batch["seq"]), np.asarray(batch["mask"])
    for i in range(seqs.shape[0]):
        n = int(mask[i].sum())
        if n == len(seq) and np.array_equal(seqs[i, :n], seq):
            return True
    return False


@pytest.mark.quick
class TestRetryPolicyUnits:
    def test_classification(self):
        rp = RetryPolicy()
        assert rp.is_transient(TransientExecutorError("x"))
        assert rp.is_transient(WatchdogTimeout("x"))
        assert rp.is_transient(RuntimeError("RESOURCE_EXHAUSTED: oom"))
        assert not rp.is_transient(ValueError("bad shape"))
        assert not rp.is_transient(FaultInjected("poison_input"))
        rp2 = RetryPolicy(transient_types=(KeyError,))
        assert rp2.is_transient(KeyError("k"))

    def test_backoff_bounded_and_jittered(self):
        rp = RetryPolicy(backoff_base_s=0.1, backoff_max_s=0.5,
                         jitter=0.5, seed=3)
        d1, d4 = rp.delay_s(1), rp.delay_s(4)
        assert 0.1 <= d1 <= 0.15
        assert 0.5 <= d4 <= 0.75              # capped then jittered
        assert RetryPolicy(jitter=0.0).delay_s(1) == 0.05

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(nan_poison_threshold=0)

    def test_quarantine_strike_threshold(self):
        q = Quarantine(registry=MetricsRegistry())
        assert not q.strike("k", threshold=2)
        assert "k" not in q
        assert q.strike("k", threshold=2)
        assert "k" in q and len(q) == 1
        assert q.strike("k", threshold=2)      # already in: stays True
        assert not q.add("k")                  # no double count
        assert q.add("j", reason="poison_input")
        assert q.reason("j") == "poison_input"

    def test_watchdog_helper(self):
        assert run_with_watchdog(lambda: 42, 1.0) == 42
        with pytest.raises(ValueError):
            run_with_watchdog(
                lambda: (_ for _ in ()).throw(ValueError("relay")), 1.0)
        with pytest.raises(WatchdogTimeout):
            run_with_watchdog(lambda: time.sleep(5.0), 0.05)


@pytest.mark.quick
class TestCircuitBreakerUnit:
    def test_open_half_open_closed_cycle(self):
        clock = [0.0]
        cb = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                            clock=lambda: clock[0],
                            registry=MetricsRegistry())
        assert cb.state == "closed" and cb.allow_submit()
        cb.record_failure()
        assert cb.state == "closed"
        cb.record_failure()
        assert cb.state == "open"
        assert not cb.allow_submit() and not cb.allow_execute()
        clock[0] = 1.0                         # cooldown elapsed
        assert cb.state == "half_open"
        assert cb.allow_submit() and cb.allow_execute()
        cb.begin_probe()
        assert not cb.allow_execute()          # one probe at a time
        cb.record_failure()                    # probe failed: re-open
        assert cb.state == "open" and cb.opens == 2
        clock[0] = 2.0
        assert cb.allow_execute()              # half-open again
        cb.begin_probe()
        cb.record_success()
        assert cb.state == "closed" and cb.closes == 1
        assert cb.allow_execute() and cb.allow_submit()

    def test_success_resets_failure_streak(self):
        cb = CircuitBreaker(failure_threshold=2,
                            registry=MetricsRegistry())
        cb.record_failure()
        cb.record_success()
        cb.record_failure()
        assert cb.state == "closed"            # streak broken, not 2/2


class TestTransientRetry:
    def test_transient_failure_retries_to_success(self):
        # first execution raises transiently, later ones succeed
        ex = StubExecutor(
            lambda batch, call:
            (_ for _ in ()).throw(TransientExecutorError("flaky"))
            if call == 1 else None)
        metrics = ServeMetrics(registry=MetricsRegistry())
        sched = make_scheduler(
            ex, RetryPolicy(max_attempts=3, backoff_base_s=0.01, seed=1),
            metrics=metrics)
        with sched:
            t1 = sched.submit(FoldRequest(seq=seq_of()))
            t2 = sched.submit(FoldRequest(seq=seq_of(base=1)))
            r1, r2 = t1.result(timeout=30), t2.result(timeout=30)
        assert r1.ok and r2.ok
        assert r1.attempts == 2 and r2.attempts == 2
        assert ex.calls == 2                   # one retry, whole batch
        res = sched.serve_stats()["resilience"]
        assert res["retries"] == 2 and res["bisections"] == 0
        assert metrics.snapshot()["retried"] == 2
        assert metrics.snapshot()["errors"] == 0

    def test_retry_exhausted_resolves_error_not_poison(self):
        ex = StubExecutor(lambda batch, call: (_ for _ in ()).throw(
            TransientExecutorError("always down")))
        sched = make_scheduler(
            ex, RetryPolicy(max_attempts=2, backoff_base_s=0.01),
            max_batch=1)
        with sched:
            r = sched.submit(FoldRequest(seq=seq_of())).result(timeout=30)
        assert r.status == "error" and "retry_exhausted" in r.error
        assert r.attempts == 2
        # NOT quarantined: a later submit of the same content re-folds
        assert sched.serve_stats()["resilience"]["quarantine"][
            "quarantined"] == 0

    def test_without_retry_policy_behavior_unchanged(self):
        ex = StubExecutor(lambda batch, call: (_ for _ in ()).throw(
            TransientExecutorError("flaky")))
        sched = make_scheduler(ex, retry=None, max_batch=1)
        with sched:
            r = sched.submit(FoldRequest(seq=seq_of())).result(timeout=30)
        assert r.status == "error" and ex.calls == 1
        assert "resilience" not in sched.serve_stats()


class TestPoisonBisection:
    @pytest.mark.parametrize("batch_size", (4, 8))
    def test_bisection_corners_single_poison(self, batch_size):
        poison = seq_of(base=7)
        ex = StubExecutor(
            lambda batch, call:
            (_ for _ in ()).throw(RuntimeError("deterministic boom"))
            if row_matches(batch, poison) else None)
        sched = make_scheduler(
            ex, RetryPolicy(max_attempts=3, backoff_base_s=0.01),
            max_batch=batch_size, max_wait_ms=100.0)
        reqs = [FoldRequest(seq=poison)] + [
            FoldRequest(seq=np.full(8, i + 1, np.int32))
            for i in range(batch_size - 1)]
        with sched:
            tickets = [sched.submit(r) for r in reqs]
            resps = [t.result(timeout=30) for t in tickets]
        assert resps[0].status == "poisoned"
        assert "poison_input" in resps[0].error
        for r in resps[1:]:                    # zero collateral damage
            assert r.ok, (r.status, r.error)
        # the poison executed <= log2(batch)+1 times total
        bound = int(math.log2(batch_size)) + 1
        assert resps[0].attempts == bound
        res = sched.serve_stats()["resilience"]
        assert res["quarantine"]["quarantined"] == 1
        assert res["bisections"] == bound - 1

    def test_quarantined_duplicate_fails_fast(self):
        poison = seq_of(base=7)
        ex = StubExecutor(
            lambda batch, call:
            (_ for _ in ()).throw(RuntimeError("boom"))
            if row_matches(batch, poison) else None)
        sched = make_scheduler(
            ex, RetryPolicy(backoff_base_s=0.01), max_batch=1)
        with sched:
            r1 = sched.submit(FoldRequest(seq=poison)).result(timeout=30)
            calls = ex.calls
            r2 = sched.submit(FoldRequest(seq=poison)).result(timeout=30)
            r3 = sched.submit(
                FoldRequest(seq=seq_of(base=3))).result(timeout=30)
        assert r1.status == "poisoned"
        assert r2.status == "poisoned" and "fail" in r2.error
        assert ex.calls == calls + 1           # only the innocent folded
        assert r3.ok

    def test_poisoned_leader_fans_out_to_followers(self):
        """Coalesced followers of a poison leader fail fast with the
        leader's terminal state instead of hanging or re-folding."""
        poison = seq_of(base=7)
        gate = threading.Event()

        def behave(batch, call):
            gate.wait(10)                      # park the batch until the
            if row_matches(batch, poison):     # follower has attached
                raise RuntimeError("boom")
            return None

        ex = StubExecutor(behave)
        cache = FoldCache(registry=MetricsRegistry())
        sched = make_scheduler(
            ex, RetryPolicy(backoff_base_s=0.01), max_batch=1,
            cache=cache)
        with sched:
            t_lead = sched.submit(FoldRequest(seq=poison))
            deadline = time.monotonic() + 5
            while sched._inflight.inflight() == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            t_follow = sched.submit(FoldRequest(seq=poison))
            gate.set()
            r_lead = t_lead.result(timeout=30)
            r_follow = t_follow.result(timeout=30)
        assert r_lead.status == "poisoned"
        assert r_follow.status == "poisoned"
        assert r_follow.source == "coalesced"


class TestNonFiniteValidation:
    def test_nan_output_quarantines_and_duplicate_fails_fast(self):
        ex = StubExecutor(lambda batch, call: "nan")
        sched = make_scheduler(
            ex, RetryPolicy(backoff_base_s=0.01), max_batch=1)
        with sched:
            seq = seq_of()
            r1 = sched.submit(FoldRequest(seq=seq)).result(timeout=30)
            calls = ex.calls
            r2 = sched.submit(FoldRequest(seq=seq)).result(timeout=30)
        assert r1.status == "poisoned" and "nonfinite_output" in r1.error
        assert r1.coords is None               # NaN never leaves as data
        assert r2.status == "poisoned" and ex.calls == calls
        res = sched.serve_stats()["resilience"]
        assert res["nonfinite_outputs"] == 1
        assert res["quarantine"]["quarantined"] == 1

    def test_nan_threshold_two_errors_first(self):
        ex = StubExecutor(lambda batch, call: "nan")
        sched = make_scheduler(
            ex, RetryPolicy(backoff_base_s=0.01, nan_poison_threshold=2),
            max_batch=1)
        with sched:
            seq = seq_of()
            r1 = sched.submit(FoldRequest(seq=seq)).result(timeout=30)
            r2 = sched.submit(FoldRequest(seq=seq)).result(timeout=30)
        assert r1.status == "error" and "nonfinite_output" in r1.error
        assert r2.status == "poisoned"         # second strike quarantines

    def test_innocent_rows_of_nan_batch_still_serve(self):
        """Validation is per-entry: only the NaN row errors, its batch
        mates resolve ok."""
        ex = StubExecutor(lambda batch, call: "nan")   # row 0 only
        sched = make_scheduler(
            ex, RetryPolicy(backoff_base_s=0.01), max_batch=2,
            max_wait_ms=100.0)
        with sched:
            t1 = sched.submit(FoldRequest(seq=seq_of(), priority=1))
            t2 = sched.submit(FoldRequest(seq=seq_of(base=1)))
            r1, r2 = t1.result(timeout=30), t2.result(timeout=30)
        assert r1.status == "poisoned"         # priority 1 = row 0
        assert r2.ok and np.isfinite(r2.coords).all()


class TestWatchdog:
    def test_watchdog_fires_rebuilds_and_recovers(self):
        hang = StubExecutor(lambda batch, call: time.sleep(3.0))
        built = []

        def factory():
            ex = StubExecutor()
            built.append(ex)
            return ex

        sched = make_scheduler(
            hang, RetryPolicy(max_attempts=2, backoff_base_s=0.01,
                              watchdog_s=0.15),
            max_batch=1, executor_factory=factory)
        with sched:
            r = sched.submit(FoldRequest(seq=seq_of())).result(timeout=30)
        assert r.ok and r.attempts == 2
        assert len(built) == 1 and built[0].calls == 1
        res = sched.serve_stats()["resilience"]
        assert res["watchdog_fires"] == 1
        assert res["executor_rebuilds"] == 1

    def test_watchdog_timeout_is_transient(self):
        assert RetryPolicy().is_transient(WatchdogTimeout("t"))


class TestCircuitBreakerScheduler:
    def test_open_degrades_then_half_open_probe_closes(self):
        broken = {"on": True}
        ex = StubExecutor(
            lambda batch, call:
            (_ for _ in ()).throw(TransientExecutorError("sys down"))
            if broken["on"] else None)
        sched = make_scheduler(
            ex, RetryPolicy(max_attempts=1, backoff_base_s=0.01,
                            breaker_threshold=2,
                            breaker_cooldown_s=0.3),
            max_batch=1)
        with sched:
            for i in range(2):
                r = sched.submit(
                    FoldRequest(seq=seq_of(base=i))).result(timeout=30)
                assert r.status == "error"
            assert sched.serve_stats()["resilience"]["breaker"][
                "state"] == "open"
            r = sched.submit(
                FoldRequest(seq=seq_of(base=9))).result(timeout=30)
            assert r.status == "degraded" and "breaker" in r.error
            broken["on"] = False
            time.sleep(0.35)                   # cooldown -> half-open
            r = sched.submit(
                FoldRequest(seq=seq_of(base=10))).result(timeout=30)
            assert r.ok                        # the probe batch
            br = sched.serve_stats()["resilience"]["breaker"]
            assert br["state"] == "closed"
            assert br["opens"] == 1 and br["closes"] == 1
        assert sched.metrics.snapshot()["degraded"] == 1

    def test_degraded_mode_still_serves_cache_hits(self):
        ex = StubExecutor()
        cache = FoldCache(registry=MetricsRegistry())
        sched = make_scheduler(
            ex, RetryPolicy(max_attempts=1, backoff_base_s=0.01,
                            breaker_threshold=1,
                            breaker_cooldown_s=60.0),
            max_batch=1, cache=cache)
        warm = seq_of(base=4)
        with sched:
            assert sched.submit(FoldRequest(seq=warm)).result(
                timeout=30).ok                 # populates the store
            ex.behave = lambda batch, call: (_ for _ in ()).throw(
                TransientExecutorError("down"))
            r = sched.submit(
                FoldRequest(seq=seq_of(base=5))).result(timeout=30)
            assert r.status == "error"         # opened the breaker
            r_hit = sched.submit(FoldRequest(seq=warm)).result(timeout=30)
            r_novel = sched.submit(
                FoldRequest(seq=seq_of(base=6))).result(timeout=30)
        assert r_hit.ok and r_hit.source == "cache"
        assert r_novel.status == "degraded"


class TestLeaderRetryFollowerOrdering:
    def test_transient_leader_failure_does_not_fan_out(self):
        """Satellite regression: a retried leader's followers resolve
        only on the leader's TERMINAL state — a transient failure must
        not propagate."""
        first_failed = threading.Event()
        release = threading.Event()

        def behave(batch, call):
            if call == 1:
                first_failed.set()
                raise TransientExecutorError("flaky once")
            release.wait(10)
            return None

        ex = StubExecutor(behave)
        cache = FoldCache(registry=MetricsRegistry())
        sched = make_scheduler(
            ex, RetryPolicy(max_attempts=3, backoff_base_s=0.05),
            max_batch=1, cache=cache)
        seq = seq_of()
        with sched:
            t_lead = sched.submit(FoldRequest(seq=seq))
            assert first_failed.wait(10)
            t_follow = sched.submit(FoldRequest(seq=seq))
            # the leader failed transiently already; the follower must
            # still be parked, not error-resolved
            time.sleep(0.1)
            assert not t_follow.done(), \
                "transient leader failure fanned out to follower"
            assert not t_lead.done()
            release.set()
            r_lead = t_lead.result(timeout=30)
            r_follow = t_follow.result(timeout=30)
        assert r_lead.ok and r_lead.attempts >= 2
        assert r_follow.ok and r_follow.source == "coalesced"
        assert np.allclose(r_lead.coords, r_follow.coords)


@pytest.mark.quick
class TestTicketTimeout:
    def test_result_timeout_raises_instead_of_blocking(self):
        t = FoldTicket("req-hang")
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="req-hang"):
            t.result(timeout=0.05)
        assert time.monotonic() - t0 < 5.0


class TestFaultPlan:
    def test_seeded_determinism(self):
        a = FaultPlan(seed=11, exec_error_rate=0.3,
                      registry=MetricsRegistry()).arm()
        b = FaultPlan(seed=11, exec_error_rate=0.3,
                      registry=MetricsRegistry()).arm()
        assert [a._hit("exec", 0.3) for _ in range(200)] == \
            [b._hit("exec", 0.3) for _ in range(200)]

    def test_disarmed_is_noop(self):
        plan = FaultPlan(seed=1, exec_error_rate=1.0,
                         registry=MetricsRegistry())
        batch = {"seq": np.zeros((1, 8), np.int32),
                 "mask": np.ones((1, 8), bool)}
        plan.on_executor_run(batch)            # disarmed: no raise
        plan.arm()
        with pytest.raises(TransientExecutorError):
            plan.on_executor_run(batch)

    def test_poison_rows_content_addressed(self):
        plan = FaultPlan(seed=1, registry=MetricsRegistry()).arm()
        poison = seq_of(base=2)
        plan.add_poison(poison, mode="raise")
        batch = {"seq": np.zeros((2, 16), np.int32),
                 "mask": np.zeros((2, 16), bool)}
        batch["seq"][1, :8] = poison
        batch["mask"][1, :8] = True
        with pytest.raises(FaultInjected, match="poison_input"):
            plan.on_executor_run(batch)
        # warmup-style all-padding batches never match
        clean = {"seq": np.zeros((2, 16), np.int32),
                 "mask": np.zeros((2, 16), bool)}
        plan.on_executor_run(clean)

    def test_corrupt_cache_bytes_hits_quarantine_path(self, tmp_path):
        plan = FaultPlan(seed=1, corrupt_rate=1.0,
                         registry=MetricsRegistry()).arm()
        cache = FoldCache(disk_dir=str(tmp_path), faults=plan,
                          registry=MetricsRegistry())
        cache.put("deadbeef", np.ones((4, 3), np.float32),
                  np.ones(4, np.float32))
        cache._mem_drop("deadbeef")            # force the disk tier
        assert cache.get("deadbeef") is None   # corrupt -> miss
        snap = cache.stats.snapshot()
        assert snap["disk_errors"] == 1 and snap["misses"] == 1
        quarantined = list(tmp_path.glob("*/*.quarantined"))
        assert len(quarantined) == 1


class TestPeerMarkdownRecovery:
    def test_cooldown_probe_marks_peer_back_up(self):
        from alphafold2_tpu import fleet

        reg = fleet.ReplicaRegistry(model_tag="v1",
                                    registry=MetricsRegistry())
        owner_cache = FoldCache(registry=MetricsRegistry())
        srv = fleet.PeerCacheServer(owner_cache, rollout=reg.rollout,
                                    replica_id="r1",
                                    metrics=MetricsRegistry()).start()
        try:
            reg.register("r0")
            reg.register("r1", peer_addr=srv.address)
            client = fleet.PeerCacheClient(
                reg, "r0", rollout=reg.rollout,
                recovery_cooldown_s=0.2, timeout_s=2.0,
                metrics=MetricsRegistry())
            k = next(f"key{i}" for i in range(1000)
                     if client.router.owner_for(f"key{i}") == "r1")
            # kill the owner; transport failures trip the markdown
            srv.stop()
            for _ in range(client.fail_threshold):
                assert client.get(k) is None
            assert not reg.is_healthy("r1")
            # probe DURING cooldown: stays down
            assert client.get(k) is None
            assert not reg.is_healthy("r1")
            # restart the replica on the same port; after the cooldown
            # the half-open probe marks it back up
            srv2 = fleet.PeerCacheServer(
                owner_cache, rollout=reg.rollout, replica_id="r1",
                host=srv.address[0], port=srv.address[1],
                metrics=MetricsRegistry()).start()
            try:
                time.sleep(0.25)
                client.get(k)      # triggers the probe (daemon thread)
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline \
                        and not reg.is_healthy("r1"):
                    time.sleep(0.01)
                assert reg.is_healthy("r1")
                assert client.recoveries == 1
                # recovered peer serves again
                v = np.ones((4, 3), np.float32)
                owner_cache.put(k, v, np.ones(4, np.float32))
                got = client.get(k)
                assert got is not None and np.allclose(got.coords, v)
            finally:
                srv2.stop()
        finally:
            try:
                srv.stop()
            except Exception:
                pass

    def test_injected_peer_faults_feed_markdown(self):
        from alphafold2_tpu import fleet

        plan = FaultPlan(seed=1, peer_error_rate=1.0,
                         registry=MetricsRegistry()).arm()
        reg = fleet.ReplicaRegistry(model_tag="v1",
                                    registry=MetricsRegistry())
        owner_cache = FoldCache(registry=MetricsRegistry())
        srv = fleet.PeerCacheServer(owner_cache, rollout=reg.rollout,
                                    replica_id="r1",
                                    metrics=MetricsRegistry()).start()
        try:
            reg.register("r0")
            reg.register("r1", peer_addr=srv.address)
            client = fleet.PeerCacheClient(
                reg, "r0", rollout=reg.rollout, faults=plan,
                metrics=MetricsRegistry())
            k = next(f"key{i}" for i in range(1000)
                     if client.router.owner_for(f"key{i}") == "r1")
            for _ in range(client.fail_threshold):
                assert client.get(k) is None   # injected, live server
            assert not reg.is_healthy("r1")
            assert plan.snapshot()["injected"]["peer_error"] >= \
                client.fail_threshold
        finally:
            srv.stop()


class TestChaosEndToEnd:
    def test_seeded_chaos_32_requests_zero_hung_tickets(self):
        """ISSUE 5 acceptance: 32 requests + 1 poison under seeded
        transient faults — every ticket reaches a terminal state, every
        innocent resolves ok, the poison is quarantined within the
        bisection bound, nothing hangs."""
        plan = FaultPlan(seed=5, exec_error_rate=0.2,
                         registry=MetricsRegistry()).arm()
        poison = seq_of(base=13)
        plan.add_poison(poison, mode="raise")
        ex = StubExecutor(faults=plan)
        cache = FoldCache(registry=MetricsRegistry())
        max_batch = 4
        sched = make_scheduler(
            ex, RetryPolicy(max_attempts=4, backoff_base_s=0.005,
                            seed=5),
            max_batch=max_batch, max_wait_ms=10.0, cache=cache)
        reqs = [FoldRequest(seq=np.full(8, (i % 16) + 1, np.int32))
                for i in range(32)]
        poison_req = FoldRequest(seq=poison)
        tickets = {}
        lock = threading.Lock()

        def submit_slice(i):
            for r in reqs[i::4]:
                t = sched.submit(r)
                with lock:
                    tickets[r.request_id] = (t, False)
            if i == 2:
                t = sched.submit(poison_req)
                with lock:
                    tickets[poison_req.request_id] = (t, True)

        with sched:
            threads = [threading.Thread(target=submit_slice, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            resolved = {}
            for rid, (ticket, is_poison) in tickets.items():
                # a hung ticket fails the run here, not the harness
                resolved[rid] = (ticket.result(timeout=60), is_poison)
        assert len(resolved) == 33
        for rid, (resp, is_poison) in resolved.items():
            if is_poison:
                assert resp.status == "poisoned", (resp.status,
                                                   resp.error)
                assert resp.attempts <= int(math.log2(max_batch)) + 1
            else:
                assert resp.ok, (rid, resp.status, resp.error)
                assert np.isfinite(resp.coords).all()
        res = sched.serve_stats()["resilience"]
        assert res["quarantine"]["quarantined"] == 1
        assert plan.snapshot()["injected"]["exec_error"] > 0
        snap = sched.metrics.snapshot()
        assert snap["errors"] == 0 and snap["shed"] == 0
        assert snap["poisoned"] == 1
