"""Ring-attention tests: exactness vs dense softmax attention on the
virtual 8-device mesh, with bias, masking, and gradient checks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from alphafold2_tpu.parallel.ring import ring_attention_sharded

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def dense_attention(q, k, v, bias=None, mask=None):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    if bias is not None:
        logits = logits + bias
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :], logits, -1e9)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, -1), v)


def make_qkv(key, b=2, h=2, n=32, d=8):
    ks = jax.random.split(key, 3)
    shape = (b, h, n, d)
    return tuple(jax.random.normal(k, shape) * 0.5 for k in ks)


def ring_mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("ring",))


class TestRingAttention:
    @pytest.mark.quick
    def test_matches_dense(self):
        q, k, v = make_qkv(jax.random.PRNGKey(0))
        mesh = ring_mesh()
        out = ring_attention_sharded(q, k, v, mesh, "ring")
        ref = dense_attention(q, k, v)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_matches_dense_with_bias(self):
        q, k, v = make_qkv(jax.random.PRNGKey(1))
        bias = jax.random.normal(jax.random.PRNGKey(2), (2, 2, 32, 32))
        mesh = ring_mesh()
        out = ring_attention_sharded(q, k, v, mesh, "ring", bias=bias)
        ref = dense_attention(q, k, v, bias=bias)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_matches_dense_with_mask(self):
        q, k, v = make_qkv(jax.random.PRNGKey(3))
        mask = jnp.ones((2, 32), dtype=bool).at[:, 24:].set(False)
        mesh = ring_mesh()
        out = ring_attention_sharded(q, k, v, mesh, "ring", mask=mask)
        ref = dense_attention(q, k, v, mask=mask)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_two_device_ring(self):
        q, k, v = make_qkv(jax.random.PRNGKey(4), n=16)
        mesh = ring_mesh(2)
        out = ring_attention_sharded(q, k, v, mesh, "ring")
        ref = dense_attention(q, k, v)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gradients_match_dense(self):
        q, k, v = make_qkv(jax.random.PRNGKey(5), n=16)
        mesh = ring_mesh(4)

        def loss_ring(qkv):
            q, k, v = qkv
            return (ring_attention_sharded(q, k, v, mesh, "ring") ** 2).sum()

        def loss_dense(qkv):
            q, k, v = qkv
            return (dense_attention(q, k, v) ** 2).sum()

        g_ring = jax.grad(loss_ring)((q, k, v))
        g_dense = jax.grad(loss_dense)((q, k, v))
        for a, b in zip(g_ring, g_dense):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_numerical_stability_large_logits(self):
        q, k, v = make_qkv(jax.random.PRNGKey(6))
        q = q * 40.0  # would overflow a naive softmax in fp16/bf16 land
        mesh = ring_mesh()
        out = ring_attention_sharded(q, k, v, mesh, "ring")
        ref = dense_attention(q, k, v)
        assert bool(jnp.isfinite(out).all())
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


class TestRingInTrunk:
    """Ring attention wired into the Evoformer (VERDICT round-1 item #3):
    with `ring_attention=True` and a mesh sharding the pair axes, the two
    triangle attentions run via parallel/ring.py; outputs and parameter
    gradients must match the dense path at all valid positions (masked
    cells carry unspecified values on both paths)."""

    def _inputs(self, key, b=2, n=16, m=3, d=32):
        ks = jax.random.split(key, 4)
        x = jax.random.normal(ks[0], (b, n, n, d)) * 0.5
        msa = jax.random.normal(ks[1], (b, m, n, d)) * 0.5
        seq_mask = jnp.ones((b, n), dtype=bool).at[:, -4:].set(False)
        pmask = seq_mask[:, :, None] & seq_mask[:, None, :]
        msa_mask = jnp.ones((b, m, n), dtype=bool) & seq_mask[:, None, :]
        return x, msa, pmask, msa_mask

    def _blocks(self):
        from alphafold2_tpu.model.evoformer import EvoformerBlock
        kw = dict(dim=32, heads=2, dim_head=16)
        return (EvoformerBlock(**kw, ring_attention=False),
                EvoformerBlock(**kw, ring_attention=True))

    def test_evoformer_block_ring_matches_dense(self):
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        x, msa, pmask, msa_mask = self._inputs(jax.random.PRNGKey(10))
        dense, ring = self._blocks()
        params = dense.init(jax.random.PRNGKey(11), x, msa,
                            mask=pmask, msa_mask=msa_mask)

        xd, md = dense.apply(params, x, msa, mask=pmask, msa_mask=msa_mask)
        mesh = make_mesh(2, 2, 2)
        with use_mesh(mesh):
            xr, mr = jax.jit(lambda p, *a: ring.apply(
                p, *a, mask=pmask, msa_mask=msa_mask))(params, x, msa)

        valid = np.asarray(pmask)[..., None]
        assert np.allclose(np.asarray(xr) * valid, np.asarray(xd) * valid,
                           atol=2e-5)
        # the MSA row attention is ALSO ring-parallel now (round-2
        # VERDICT next-round #5) — match at valid MSA positions
        mvalid = np.asarray(msa_mask)[..., None]
        assert np.allclose(np.asarray(mr) * mvalid,
                           np.asarray(md) * mvalid, atol=2e-5)

    def test_evoformer_block_ring_grads_match_dense(self):
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        x, msa, pmask, msa_mask = self._inputs(jax.random.PRNGKey(12))
        dense, ring = self._blocks()
        params = dense.init(jax.random.PRNGKey(13), x, msa,
                            mask=pmask, msa_mask=msa_mask)

        def masked_loss(block):
            def loss(p):
                xo, mo = block.apply(p, x, msa, mask=pmask,
                                     msa_mask=msa_mask)
                return ((xo * pmask[..., None]) ** 2).sum() + \
                    ((mo * msa_mask[..., None]) ** 2).sum()
            return loss

        g_dense = jax.grad(masked_loss(dense))(params)
        mesh = make_mesh(2, 2, 2)
        with use_mesh(mesh):
            g_ring = jax.jit(jax.grad(masked_loss(ring)))(params)

        flat_d, _ = jax.tree_util.tree_flatten(g_dense)
        flat_r, _ = jax.tree_util.tree_flatten(g_ring)
        for a, b in zip(flat_r, flat_d):
            # float-reassociation noise from the ring's blockwise
            # accumulation: observed ~2e-4 absolute on grads of |.|~1e2
            # under a sum-of-squares loss (~1e-9 of the loss scale)
            assert np.allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-3), \
                float(jnp.abs(a - b).max())

    def test_evoformer_stack_ring_smoke(self):
        # depth-2 scanned stack with ring enabled compiles and runs under
        # the mesh; outputs match the dense stack at valid positions
        from alphafold2_tpu.model.evoformer import Evoformer
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        x, msa, pmask, msa_mask = self._inputs(jax.random.PRNGKey(14))
        kw = dict(dim=32, depth=2, heads=2, dim_head=16)
        dense = Evoformer(**kw, ring_attention=False)
        ring = Evoformer(**kw, ring_attention=True)
        params = dense.init(jax.random.PRNGKey(15), x, msa,
                            mask=pmask, msa_mask=msa_mask)

        xd, _ = dense.apply(params, x, msa, mask=pmask, msa_mask=msa_mask)
        mesh = make_mesh(2, 2, 2)
        with use_mesh(mesh):
            xr, _ = jax.jit(lambda p: ring.apply(
                p, x, msa, mask=pmask, msa_mask=msa_mask))(params)

        valid = np.asarray(pmask)[..., None]
        assert np.allclose(np.asarray(xr) * valid, np.asarray(xd) * valid,
                           atol=5e-5)


class TestMsaRowRing:
    """AxialAttention with ring_axes=(None, 'i'): the MSA row attention
    layout — alignment rows local, the residue axis ring-sharded — with
    per-alignment (non-separable) masks honored exactly."""

    def test_matches_dense_with_per_row_mask(self):
        from alphafold2_tpu.model.primitives import AxialAttention
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        b, m, n, dim = 2, 3, 16, 32
        ks = jax.random.split(jax.random.PRNGKey(40), 3)
        x = jax.random.normal(ks[0], (b, m, n, dim)) * 0.5
        # per-alignment gaps: a genuinely different mask in every row
        mask = jax.random.bernoulli(ks[1], 0.7, (b, m, n))
        mask = mask.at[..., :2].set(True)

        dense = AxialAttention(dim=dim, heads=2, dim_head=16,
                               row_attn=True, col_attn=False)
        ring = AxialAttention(dim=dim, heads=2, dim_head=16,
                              row_attn=True, col_attn=False,
                              ring_axes=(None, "i"))
        from conftest import perturb_params
        params = perturb_params(dense.init(ks[2], x, mask=mask),
                                jax.random.PRNGKey(41))

        out_dense = dense.apply(params, x, mask=mask)
        mesh = make_mesh(2, 2, 2)
        with use_mesh(mesh):
            out_ring = jax.jit(
                lambda p: ring.apply(p, x, mask=mask))(params)

        valid = np.asarray(mask)[..., None]
        assert float(np.abs(np.asarray(out_dense)).max()) > 0
        assert np.allclose(np.asarray(out_ring) * valid,
                           np.asarray(out_dense) * valid, atol=2e-5)


class TestReversibleRing:
    """reversible=True + ring_attention=True (the round-2 assert is
    lifted): forward and parameter gradients match the off-mesh
    reversible trunk at valid positions."""

    def _inputs(self, key, b=2, n=16, m=3, d=32):
        ks = jax.random.split(key, 2)
        x = jax.random.normal(ks[0], (b, n, n, d)) * 0.5
        msa = jax.random.normal(ks[1], (b, m, n, d)) * 0.5
        seq_mask = jnp.ones((b, n), dtype=bool).at[:, -4:].set(False)
        pmask = seq_mask[:, :, None] & seq_mask[:, None, :]
        msa_mask = jnp.ones((b, m, n), dtype=bool) & seq_mask[:, None, :]
        return x, msa, pmask, msa_mask

    def test_forward_and_grads_match_off_mesh(self):
        from alphafold2_tpu.model.evoformer import Evoformer
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        x, msa, pmask, msa_mask = self._inputs(jax.random.PRNGKey(50))
        kw = dict(dim=32, depth=2, heads=2, dim_head=16, reversible=True)
        plain = Evoformer(**kw, ring_attention=False)
        ring = Evoformer(**kw, ring_attention=True)
        params = plain.init(jax.random.PRNGKey(51), x, msa,
                            mask=pmask, msa_mask=msa_mask)

        def masked_loss(model):
            def loss(p):
                xo, mo = model.apply(p, x, msa, mask=pmask,
                                     msa_mask=msa_mask)
                return ((xo * pmask[..., None]) ** 2).sum() + \
                    ((mo * msa_mask[..., None]) ** 2).sum()
            return loss

        l_plain, g_plain = jax.value_and_grad(masked_loss(plain))(params)
        mesh = make_mesh(2, 2, 2)
        with use_mesh(mesh):
            l_ring, g_ring = jax.jit(
                jax.value_and_grad(masked_loss(ring)))(params)

        assert np.allclose(float(l_plain), float(l_ring), rtol=1e-5)
        flat_p, _ = jax.tree_util.tree_flatten(g_plain)
        flat_r, _ = jax.tree_util.tree_flatten(g_ring)
        for a, b_ in zip(flat_r, flat_p):
            assert np.allclose(np.asarray(a), np.asarray(b_),
                               rtol=1e-5, atol=1e-3), \
                float(jnp.abs(a - b_).max())


class TestRotary:
    def test_rotate_every_two(self):
        from alphafold2_tpu.model.rotary import rotate_every_two
        x = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        out = rotate_every_two(x)
        assert np.allclose(out, [-2.0, 1.0, -4.0, 3.0])

    def test_rotary_preserves_norm(self):
        from alphafold2_tpu.model.rotary import (
            apply_rotary_pos_emb, fixed_positional_embedding)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32))
        sin, cos = fixed_positional_embedding(16, 32)
        y = apply_rotary_pos_emb(x, (sin, cos))
        assert np.allclose(jnp.linalg.norm(y, axis=-1),
                           jnp.linalg.norm(x, axis=-1), atol=1e-4)

    def test_rotary_relative_property(self):
        # <rot(q, i), rot(k, j)> depends only on i - j
        from alphafold2_tpu.model.rotary import (
            apply_rotary_pos_emb, fixed_positional_embedding)
        d = 16
        q = jax.random.normal(jax.random.PRNGKey(1), (d,))
        k = jax.random.normal(jax.random.PRNGKey(2), (d,))
        sin, cos = fixed_positional_embedding(32, d)
        rot = lambda v, i: apply_rotary_pos_emb(v, (sin[i], cos[i]))
        dot_a = jnp.dot(rot(q, 5), rot(k, 3))
        dot_b = jnp.dot(rot(q, 12), rot(k, 10))
        assert np.isclose(float(dot_a), float(dot_b), atol=1e-4)

    def test_axial_rotary_shapes(self):
        from alphafold2_tpu.model.rotary import axial_rotary_embedding
        sin, cos = axial_rotary_embedding(6, 8, 16)
        assert sin.shape == (6, 8, 16) and cos.shape == (6, 8, 16)


class TestPairRowRing:
    def test_matches_dense_row_attention(self):
        from alphafold2_tpu.parallel.ring import pair_row_attention_sharded
        b, h, I, J, d = 1, 2, 8, 16, 8
        ks = jax.random.split(jax.random.PRNGKey(30), 4)
        q = jax.random.normal(ks[0], (b, h, I, J, d)) * 0.5
        k = jax.random.normal(ks[1], (b, h, I, J, d)) * 0.5
        v = jax.random.normal(ks[2], (b, h, I, J, d))
        bias = jax.random.normal(ks[3], (b, h, J, J))

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("i", "j"))
        out = pair_row_attention_sharded(q, k, v, bias, mesh)

        # dense reference: per-row attention along J with shared (J,J) bias
        logits = jnp.einsum("bhiqd,bhikd->bhiqk", q, k) + bias[:, :, None]
        ref = jnp.einsum("bhiqk,bhikd->bhiqd",
                         jax.nn.softmax(logits, -1), v)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_with_column_mask(self):
        from alphafold2_tpu.parallel.ring import pair_row_attention_sharded
        b, h, I, J, d = 1, 2, 4, 8, 8
        ks = jax.random.split(jax.random.PRNGKey(31), 4)
        q = jax.random.normal(ks[0], (b, h, I, J, d)) * 0.5
        k = jax.random.normal(ks[1], (b, h, I, J, d)) * 0.5
        v = jax.random.normal(ks[2], (b, h, I, J, d))
        bias = jax.random.normal(ks[3], (b, h, J, J))
        col = jnp.ones((b, J), dtype=bool).at[:, 6:].set(False)
        mask = jnp.broadcast_to(col[:, None, :], (b, I, J))

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("i", "j"))
        out = pair_row_attention_sharded(q, k, v, bias, mesh, mask=mask)

        logits = jnp.einsum("bhiqd,bhikd->bhiqk", q, k) + bias[:, :, None]
        logits = jnp.where(mask[:, None, :, None, :], logits, -1e9)
        ref = jnp.einsum("bhiqk,bhikd->bhiqd",
                         jax.nn.softmax(logits, -1), v)
        assert np.allclose(np.asarray(out)[:, :, :, :6],
                           np.asarray(ref)[:, :, :, :6], atol=1e-5)

    @pytest.mark.quick
    def test_with_nonseparable_mask(self):
        """Per-row key masks that are NOT an outer product of axis
        vectors are honored exactly (round-2 VERDICT weak #5)."""
        from alphafold2_tpu.parallel.ring import pair_row_attention_sharded
        b, h, I, J, d = 1, 2, 4, 8, 8
        ks = jax.random.split(jax.random.PRNGKey(33), 4)
        q = jax.random.normal(ks[0], (b, h, I, J, d)) * 0.5
        k = jax.random.normal(ks[1], (b, h, I, J, d)) * 0.5
        v = jax.random.normal(ks[2], (b, h, I, J, d))
        # random per-(row, key) mask; keys 0-1 always valid so every
        # query row has something to attend to
        mask = jax.random.bernoulli(ks[3], 0.6, (b, I, J))
        mask = mask.at[..., :2].set(True)
        assert not bool(jnp.array_equal(  # actually non-separable
            mask, mask.any(1, keepdims=True) & mask.any(2, keepdims=True)))

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("i", "j"))
        out = pair_row_attention_sharded(q, k, v, None, mesh, mask=mask)

        logits = jnp.einsum("bhiqd,bhikd->bhiqk", q, k)
        logits = jnp.where(mask[:, None, :, None, :], logits, -1e9)
        ref = jnp.einsum("bhiqk,bhikd->bhiqd",
                         jax.nn.softmax(logits, -1), v)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_batch_one_on_data_mesh(self):
        """batch=1 on a data=2 training mesh: the data axis cannot divide
        the batch, so it must quietly fall back to replication rather
        than raise at trace time."""
        from alphafold2_tpu.parallel import make_mesh
        from alphafold2_tpu.parallel.ring import pair_row_attention_sharded
        b, h, I, J, d = 1, 2, 4, 8, 8
        ks = jax.random.split(jax.random.PRNGKey(35), 3)
        q = jax.random.normal(ks[0], (b, h, I, J, d)) * 0.5
        k = jax.random.normal(ks[1], (b, h, I, J, d)) * 0.5
        v = jax.random.normal(ks[2], (b, h, I, J, d))

        mesh = make_mesh(2, 2, 2)
        out = pair_row_attention_sharded(q, k, v, None, mesh)

        logits = jnp.einsum("bhiqd,bhikd->bhiqk", q, k)
        ref = jnp.einsum("bhiqk,bhikd->bhiqd",
                         jax.nn.softmax(logits, -1), v)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_unsharded_row_axis(self):
        """i_axis=None: rows local (the MSA layout), keys ring over j."""
        from alphafold2_tpu.parallel.ring import pair_row_attention_sharded
        b, h, M, J, d = 1, 2, 3, 16, 8
        ks = jax.random.split(jax.random.PRNGKey(34), 3)
        q = jax.random.normal(ks[0], (b, h, M, J, d)) * 0.5
        k = jax.random.normal(ks[1], (b, h, M, J, d)) * 0.5
        v = jax.random.normal(ks[2], (b, h, M, J, d))

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("i", "j"))
        out = pair_row_attention_sharded(q, k, v, None, mesh,
                                         i_axis=None, j_axis="j")

        logits = jnp.einsum("bhiqd,bhikd->bhiqk", q, k)
        ref = jnp.einsum("bhiqk,bhikd->bhiqd",
                         jax.nn.softmax(logits, -1), v)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gradients_match_dense(self):
        from alphafold2_tpu.parallel.ring import pair_row_attention_sharded
        b, h, I, J, d = 1, 2, 4, 8, 8
        ks = jax.random.split(jax.random.PRNGKey(32), 4)
        q = jax.random.normal(ks[0], (b, h, I, J, d)) * 0.5
        k = jax.random.normal(ks[1], (b, h, I, J, d)) * 0.5
        v = jax.random.normal(ks[2], (b, h, I, J, d))
        bias = jax.random.normal(ks[3], (b, h, J, J))
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("i", "j"))

        def loss_ring(args):
            q, k, v, bias = args
            return (pair_row_attention_sharded(q, k, v, bias, mesh) ** 2
                    ).sum()

        def loss_dense(args):
            q, k, v, bias = args
            logits = jnp.einsum("bhiqd,bhikd->bhiqk", q, k) + \
                bias[:, :, None]
            out = jnp.einsum("bhiqk,bhikd->bhiqd",
                             jax.nn.softmax(logits, -1), v)
            return (out ** 2).sum()

        g_ring = jax.grad(loss_ring)((q, k, v, bias))
        g_dense = jax.grad(loss_dense)((q, k, v, bias))
        for a, b_ in zip(g_ring, g_dense):
            assert np.allclose(np.asarray(a), np.asarray(b_), atol=1e-4)


class TestPairRowRingDropout:
    """Round-4 VERDICT #5: training-time attention dropout runs INSIDE the
    ring instead of silently de-ringing the long-context path. The ring's
    realized mask derivation is replayed densely by
    `pair_row_dropout_mask` (shared fold_in recipe); these tests then
    independently verify the ring's distribution semantics — numerator-only
    drop, undropped row_sum normalizer, 1/(1-rate) scaling — and gradient
    flow against a plain dense implementation of
    `dropout(softmax(logits)) @ v` using that replayed mask."""

    def _setup(self, seed=40, b=1, h=2, I=8, J=8, d=8):
        ks = jax.random.split(jax.random.PRNGKey(seed), 5)
        q = jax.random.normal(ks[0], (b, h, I, J, d)) * 0.5
        k = jax.random.normal(ks[1], (b, h, I, J, d)) * 0.5
        v = jax.random.normal(ks[2], (b, h, I, J, d))
        bias = jax.random.normal(ks[3], (b, h, J, J))
        return q, k, v, bias, ks[4]

    @staticmethod
    def _dense_dropped(q, k, v, bias, keep, rate, mask=None):
        logits = jnp.einsum("bhiqd,bhikd->bhiqk", q, k)
        if bias is not None:
            logits = logits + bias[:, :, None]
        if mask is not None:
            logits = jnp.where(mask[:, None, :, None, :], logits, -1e9)
        probs = jax.nn.softmax(logits, -1)
        probs = probs * keep / (1.0 - rate)
        return jnp.einsum("bhiqk,bhikd->bhiqd", probs, v)

    @pytest.mark.quick
    def test_matches_dense_replay(self):
        from alphafold2_tpu.parallel.ring import (pair_row_attention_sharded,
                                                  pair_row_dropout_mask)
        q, k, v, bias, dkey = self._setup()
        rate = 0.4
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("i", "j"))
        out = pair_row_attention_sharded(q, k, v, bias, mesh,
                                         dropout_rate=rate,
                                         dropout_key=dkey)
        keep = pair_row_dropout_mask(dkey, rate, b=1, h=2, i_blocks=2,
                                     j_blocks=2, il=4, jl=4)
        ref = self._dense_dropped(q, k, v, bias, keep, rate)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        # and it actually dropped something (differs from no-dropout)
        ref_nodrop = self._dense_dropped(q, k, v, bias,
                                         jnp.ones_like(keep), 0.0)
        assert not np.allclose(np.asarray(out), np.asarray(ref_nodrop),
                               atol=1e-3)

    def test_unsharded_row_axis_with_mask(self):
        """MSA layout (i_axis=None) + non-separable key mask + dropout."""
        from alphafold2_tpu.parallel.ring import (pair_row_attention_sharded,
                                                  pair_row_dropout_mask)
        q, k, v, _, dkey = self._setup(seed=41, I=3, J=16)
        rate = 0.25
        mask = jax.random.bernoulli(jax.random.PRNGKey(7), 0.7, (1, 3, 16))
        mask = mask.at[..., :2].set(True)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("i", "j"))
        out = pair_row_attention_sharded(q, k, v, None, mesh,
                                         i_axis=None, j_axis="j",
                                         mask=mask, dropout_rate=rate,
                                         dropout_key=dkey)
        keep = pair_row_dropout_mask(dkey, rate, b=1, h=2, i_blocks=None,
                                     j_blocks=4, il=3, jl=4)
        ref = self._dense_dropped(q, k, v, None, keep, rate, mask=mask)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gradients_match_dense_replay(self):
        from alphafold2_tpu.parallel.ring import (pair_row_attention_sharded,
                                                  pair_row_dropout_mask)
        q, k, v, bias, dkey = self._setup(seed=42)
        rate = 0.3
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("i", "j"))
        keep = pair_row_dropout_mask(dkey, rate, b=1, h=2, i_blocks=2,
                                     j_blocks=2, il=4, jl=4)

        def loss_ring(args):
            q, k, v, bias = args
            return (pair_row_attention_sharded(
                q, k, v, bias, mesh, dropout_rate=rate,
                dropout_key=dkey) ** 2).sum()

        def loss_dense(args):
            q, k, v, bias = args
            return (self._dense_dropped(q, k, v, bias, keep, rate) ** 2
                    ).sum()

        g_ring = jax.grad(loss_ring)((q, k, v, bias))
        g_dense = jax.grad(loss_dense)((q, k, v, bias))
        for a, b_ in zip(g_ring, g_dense):
            assert np.allclose(np.asarray(a), np.asarray(b_), atol=1e-4)

    @pytest.mark.quick
    def test_axial_attention_stays_ringed_under_dropout(self):
        """The module-level regression: AxialAttention with dropout active
        in a training trace must STILL dispatch to the ring (it used to
        silently fall back to the dense/GSPMD path)."""
        import alphafold2_tpu.parallel.ring as ring_mod
        from alphafold2_tpu.model.primitives import AxialAttention
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        b, n, d = 1, 8, 16
        x = jax.random.normal(jax.random.PRNGKey(50), (b, n, n, d)) * 0.5
        attn = AxialAttention(dim=d, heads=2, dim_head=8, row_attn=True,
                              col_attn=False, dropout=0.3,
                              ring_axes=("i", "j"))
        params = attn.init(jax.random.PRNGKey(51), x)

        calls = []
        orig = ring_mod.pair_row_attention_sharded

        def spy(*args, **kwargs):
            calls.append(kwargs.get("dropout_rate", 0.0))
            return orig(*args, **kwargs)

        mesh = make_mesh(2, 2, 2)
        ring_mod.pair_row_attention_sharded = spy
        try:
            with use_mesh(mesh):
                out = attn.apply(params, x, deterministic=False,
                                 rngs={"dropout": jax.random.PRNGKey(52)})
        finally:
            ring_mod.pair_row_attention_sharded = orig
        assert calls and calls[0] == 0.3, \
            "dropout-active trace did not take the ring path"
        assert np.isfinite(np.asarray(out)).all()
