"""Per-bucket kernel selection tests (ISSUE 12): KernelSpec/
contact-prior mask planning units, the executor's 8-tuple ExecKey
kernel element (stale-kernel staleness regression), serving-level
numerics equivalence of the block-sparse kernel vs the dense path
(executor + end-to-end scheduler), the kernel_policy=None
scrubbed-stats identity pin, the contact-prior step re-lowering flow,
and KernelPolicy.parse / config threading."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import perturb_params
from alphafold2_tpu import Alphafold2
from alphafold2_tpu.config import ModelConfig
from alphafold2_tpu.data.synthetic import synthetic_requests
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.ops.block_sparse import (KernelSpec,
                                             contact_block_pattern,
                                             contact_probs_from_distogram,
                                             plan_block_pattern)
from alphafold2_tpu.serve import (BucketPolicy, FoldExecutor,
                                  FoldRequest, KernelPolicy,
                                  RecyclePolicy, Scheduler,
                                  SchedulerConfig, ServeMetrics)

MSA_DEPTH = 3


@pytest.fixture(scope="module")
def model_and_params():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1)
    n = 16
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
        mask=jnp.ones((1, n), bool),
        msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
    # zero-init output projections make every backend trivially equal;
    # perturb so numerics comparisons actually compare attention paths
    return model, perturb_params(params, jax.random.PRNGKey(5))


def requests_of(lengths, key=1):
    return synthetic_requests(jax.random.PRNGKey(key),
                              num=len(lengths), lengths=lengths,
                              msa_depth=MSA_DEPTH)


def _scheduler(model_and_params, buckets=(16,), num_recycles=1,
               max_entries=16, **kw):
    kw.setdefault("metrics", ServeMetrics(registry=MetricsRegistry()))
    kw.setdefault("registry", MetricsRegistry())
    ex = FoldExecutor(*model_and_params, max_entries=max_entries)
    return Scheduler(
        ex, BucketPolicy(buckets),
        SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                        num_recycles=num_recycles, msa_depth=MSA_DEPTH),
        **kw)


ALL_LIVE = dict(window=16, num_global=1)    # every block live
SPARSE = dict(window=0, num_global=0)       # diagonal only


class TestKernelSpec:
    @pytest.mark.quick
    def test_banded_spec_basics(self):
        spec = KernelSpec.banded(32, 8, window=1, num_global=1)
        assert spec.n == 32 and spec.covers(32) and not spec.covers(16)
        assert 0.0 < spec.live_fraction < 1.0
        # hashable + stable label; backend is part of the label (a
        # masked and a pallas build of the same pattern are different
        # compiled programs)
        assert hash(spec) == hash(KernelSpec.banded(32, 8))
        assert spec.label == KernelSpec.banded(32, 8).label
        assert spec.label != KernelSpec.banded(
            32, 8, backend="masked").label
        assert spec.label != KernelSpec.banded(32, 8, window=2).label

    @pytest.mark.quick
    def test_spec_refuses_empty_row(self):
        bad = np.zeros((2, 2), bool)
        bad[0, 0] = True                    # row 1 has no live block
        with pytest.raises(ValueError):
            KernelSpec.from_pattern(bad, 8)

    @pytest.mark.quick
    def test_token_mask_expands_pattern(self):
        spec = KernelSpec.banded(16, 8, **SPARSE)
        tok = spec.token_mask()
        assert tok.shape == (16, 16)
        assert tok[:8, :8].all() and not tok[:8, 8:].any()

    @pytest.mark.quick
    def test_resolve_backend_cpu(self):
        # CPU: auto never silently picks the interpret-mode kernel
        assert KernelSpec.banded(16, 8).resolve_backend() == "masked"
        assert KernelSpec.banded(
            16, 8, backend="pallas").resolve_backend() == "pallas"


class TestContactPlanning:
    @pytest.mark.quick
    def test_zero_contacts_keep_min_one_live_block(self):
        """min-1-live guard: even a contact map with NO contacts plans
        a pattern every q-block can softmax over (the diagonal band),
        so plan_block_pattern never sees an empty row."""
        pattern = contact_block_pattern(np.zeros((32, 32)), 8,
                                        window=0, num_global=0)
        assert pattern.diagonal().all()
        cols, valid = plan_block_pattern(pattern)   # would raise
        assert valid[:, 0].all()

    @pytest.mark.quick
    def test_contacts_add_support_and_symmetrize(self):
        contacts = np.zeros((32, 32))
        contacts[2, 28] = 0.9               # one off-diagonal contact
        pattern = contact_block_pattern(contacts, 8, threshold=0.5,
                                        window=0, num_global=0)
        assert pattern[0, 3] and pattern[3, 0]     # symmetrized
        assert not pattern[1, 3]

    @pytest.mark.quick
    def test_live_frac_budget_mode(self):
        rng = np.random.default_rng(0)
        contacts = rng.uniform(size=(64, 64))
        pattern = contact_block_pattern(contacts, 8, live_frac=0.25,
                                        window=0, num_global=0)
        # the diagonal floor and symmetrization only ADD support over
        # the 25% budget (worst case: budget doubled + diagonal)
        assert 0.25 <= pattern.mean() <= 0.25 * 2 + 0.125

    @pytest.mark.quick
    def test_distogram_probs_shape_and_batch_max(self):
        logits = np.zeros((2, 16, 16, 37), np.float32)
        logits[1, 3, 12, 0] = 50.0          # element 1: certain contact
        probs = contact_probs_from_distogram(logits, cutoff=8.0)
        assert probs.shape == (16, 16)
        assert probs[3, 12] > 0.9           # max over batch kept it

    @pytest.mark.quick
    def test_degenerate_all_dense_falls_back_to_dense(self):
        """An all-contact map plans an all-live pattern — the policy
        answers None (run the DENSE kernel) instead of paying sparse
        overhead for zero FLOP savings; same rule for a static mask
        whose banded window covers everything."""
        pol = KernelPolicy(table={32: "blocksparse"}, block=8,
                           window=0, num_global=0)
        dist = np.zeros((1, 32, 32, 37), np.float32)
        dist[..., 0] = 50.0                 # every pair in contact
        assert pol.contact_spec_for(32, dist) is None
        wide = KernelPolicy(table={32: "blocksparse"}, block=8,
                            window=8, num_global=1)
        assert wide.spec_for(32) is None
        assert wide.kernel_for(32) == "dense"

    @pytest.mark.quick
    def test_contact_spec_for_sparse_map(self):
        pol = KernelPolicy(table={32: "blocksparse"}, block=8,
                           window=0, num_global=0,
                           contact_threshold=0.5)
        dist = np.zeros((1, 32, 32, 37), np.float32)
        dist[..., -1] = 50.0                # everything far apart
        spec = pol.contact_spec_for(32, dist)
        assert spec is not None and spec.source == "contact"
        assert spec.live_fraction < 0.5


class TestKernelPolicy:
    @pytest.mark.quick
    def test_parse_surfaces(self):
        edges = (64, 512)
        assert KernelPolicy.parse("", edges) is None
        allsparse = KernelPolicy.parse("blocksparse", edges, block=64)
        assert allsparse.table == {64: "blocksparse",
                                   512: "blocksparse"}
        pinned = KernelPolicy.parse("64=dense,512=sparse", edges,
                                    block=64)
        assert pinned.kernel_for(64) == "dense"
        assert pinned.kernel_for(512) == "blocksparse"
        with pytest.raises(ValueError):
            KernelPolicy.parse("64=warp", edges)

    @pytest.mark.quick
    def test_auto_routes_by_static_live_fraction(self):
        # block 64: edge 128 is 2x2 blocks (banded mask all-live ->
        # dense); edge 1024 is 16x16 (live frac ~0.3 -> sparse)
        pol = KernelPolicy.parse("auto", (128, 1024), block=64,
                                 sparse_live_frac=0.5)
        assert pol.kernel_for(128) == "dense"
        assert pol.kernel_for(1024) == "blocksparse"

    @pytest.mark.quick
    def test_indivisible_bucket_serves_dense(self):
        pol = KernelPolicy(table={48: "blocksparse"}, block=32)
        assert pol.spec_for(48) is None
        assert pol.kernel_for(48) == "dense"

    @pytest.mark.quick
    def test_from_model_config_threads_sparse_knobs(self):
        cfg = ModelConfig(sparse_block=8, sparse_num_global=1,
                          sparse_window=0)
        pol = KernelPolicy.from_model_config(cfg, (64,),
                                             sparse_live_frac=0.5)
        assert pol.block == 8 and pol.window == 0
        spec = pol.spec_for(64)
        assert spec is not None and spec.block == 8

    @pytest.mark.quick
    def test_snapshot_reports_routing(self):
        pol = KernelPolicy(table={16: "dense", 32: "blocksparse"},
                           block=8, window=0)
        snap = pol.snapshot()
        assert snap["buckets"]["16"]["kernel"] == "dense"
        assert snap["buckets"]["32"]["kernel"] == "blocksparse"
        assert 0 < snap["buckets"]["32"]["live_frac"] < 1


class TestExecutorKernelKeys:
    def test_exec_key_grows_kernel_element(self, model_and_params):
        """MIGRATING ISSUE-12: the 8-tuple. Dense runs key "dense";
        kernel'd runs key the spec label — both resident in the LRU at
        once, so a policy flip re-lowers instead of serving stale."""
        ex = FoldExecutor(*model_and_params, max_entries=8)
        policy = BucketPolicy((16,))
        batch, _ = policy.assemble(requests_of((12,)), 16, 2,
                                   msa_depth=MSA_DEPTH)
        spec = KernelSpec.banded(16, 8, **ALL_LIVE)
        k_dense = ex.key_for(batch, 0)
        k_spec = ex.key_for(batch, 0, kernel=spec)
        assert len(k_dense) == len(k_spec) == 8
        assert k_dense[7] == "dense" and k_spec[7] == spec.label
        assert k_dense[:7] == k_spec[:7]

    def test_legacy_key_normalization(self, model_and_params):
        ex = FoldExecutor(*model_and_params)
        assert ex._normalize_key((16, 1, 3, 0))[7] == "dense"
        assert ex._normalize_key(
            (16, 1, 3, 0, (1, 1), "tag", "step"))[7] == "dense"
        full = (16, 1, 3, 0, (1, 1), "tag", "step", "bs8x2-sabc")
        assert ex._normalize_key(full) == full

    def test_kernel_flip_compiles_fresh_then_hits(self,
                                                  model_and_params):
        """The staleness regression: a different spec (a policy flip or
        a contact re-plan) is a different executable — never a stale
        serve; flipping BACK hits the still-resident original."""
        ex = FoldExecutor(*model_and_params, max_entries=8)
        policy = BucketPolicy((16,))
        batch, _ = policy.assemble(requests_of((12,)), 16, 2,
                                   msa_depth=MSA_DEPTH)
        a = KernelSpec.banded(16, 8, **ALL_LIVE)
        b = KernelSpec.banded(16, 8, **SPARSE)
        ex.run(batch, 0, kernel=a)
        ex.run(batch, 0, kernel=b)
        ex.run(batch, 0)                    # dense is its own key too
        assert ex.stats()["misses"] == 3
        ex.run(batch, 0, kernel=a)
        assert ex.stats()["hits"] == 1

    def test_warmup_precompiles_kernel_variant(self, model_and_params):
        ex = FoldExecutor(*model_and_params, max_entries=8)
        spec = KernelSpec.banded(16, 8, **ALL_LIVE)
        fresh = ex.warmup([(16, 2, MSA_DEPTH, 0)], kernel=spec)
        assert fresh == 1
        policy = BucketPolicy((16,))
        batch, _ = policy.assemble(requests_of((12,)), 16, 2,
                                   msa_depth=MSA_DEPTH)
        ex.run(batch, 0, kernel=spec)
        stats = ex.stats()
        assert stats["misses"] == 1 and stats["hits"] == 1
        assert any(k[7] == spec.label for k in stats["keys"])

    @staticmethod
    def _real_diff(a, b, lengths):
        """Max |coords delta| over REAL residues only: padded rows are
        masked-query positions — unspecified on every backend (the
        scheduler never serves them), so equivalence is defined on the
        positions that reach callers."""
        return max(float(np.abs(np.asarray(a.coords)[i, :n]
                                - np.asarray(b.coords)[i, :n]).max())
                   for i, n in enumerate(lengths))

    def test_executor_numerics_all_live_matches_dense(
            self, model_and_params):
        """Serving-level equivalence at the executor: an ALL-LIVE
        pattern through the block-skipping kernel (interpret mode on
        CPU) computes full attention — within float tolerance of the
        dense executable on every real residue; the masked-dense
        backend is bit-identical to dense (a bias of zeros)."""
        ex = FoldExecutor(*model_and_params, max_entries=8)
        policy = BucketPolicy((16,))
        lengths = (12, 9)
        batch, _ = policy.assemble(requests_of(lengths), 16, 2,
                                   msa_depth=MSA_DEPTH)
        dense = ex.run(batch, 1)
        masked = ex.run(batch, 1, kernel=KernelSpec.banded(
            16, 8, backend="masked", **ALL_LIVE))
        pallas = ex.run(batch, 1, kernel=KernelSpec.banded(
            16, 8, backend="pallas", **ALL_LIVE))
        assert self._real_diff(masked, dense, lengths) == 0.0
        assert self._real_diff(pallas, dense, lengths) < 5e-4
        for i, n in enumerate(lengths):
            np.testing.assert_allclose(
                np.asarray(pallas.confidence)[i, :n],
                np.asarray(dense.confidence)[i, :n], atol=5e-4)

    def test_executor_numerics_sparse_backends_agree(
            self, model_and_params):
        """A genuinely sparse pattern: the FLOP-skipping kernel and the
        masked-dense reference agree tightly with each other on every
        real residue and BOTH differ from unrestricted dense (the
        pattern is really applied)."""
        ex = FoldExecutor(*model_and_params, max_entries=8)
        policy = BucketPolicy((16,))
        lengths = (12, 9)
        batch, _ = policy.assemble(requests_of(lengths), 16, 2,
                                   msa_depth=MSA_DEPTH)
        dense = ex.run(batch, 1)
        masked = ex.run(batch, 1, kernel=KernelSpec.banded(
            16, 4, backend="masked", **SPARSE))
        pallas = ex.run(batch, 1, kernel=KernelSpec.banded(
            16, 4, backend="pallas", **SPARSE))
        assert self._real_diff(pallas, masked, lengths) < 5e-4
        assert self._real_diff(masked, dense, lengths) > 1e-3


class TestSchedulerKernelRouting:
    def test_end_to_end_routing_and_equivalence(self, model_and_params):
        """Scheduler-level: a policy routing the long bucket
        blocksparse serves every request ok; the dense bucket's outputs
        are BYTE-identical to a policy-less scheduler, the sparse
        bucket's masked and pallas backends agree within tight
        tolerance, and serve_stats()["kernel"] counts both kinds."""
        reqs = requests_of((12, 28, 9, 26), key=3)

        def run_one(kp):
            sched = _scheduler(model_and_params, buckets=(16, 32),
                               kernel_policy=kp)
            assert sched.warmup() >= 1
            with sched:
                resps = [sched.submit(
                    FoldRequest(seq=r.seq, msa=r.msa)).result(
                        timeout=300) for r in reqs]
            assert all(r.ok for r in resps)
            return resps, sched.serve_stats()

        mk = lambda backend: KernelPolicy(  # noqa: E731
            table={16: "dense", 32: "blocksparse"}, block=8,
            window=0, num_global=1, backend=backend)
        r_masked, snap = run_one(mk("masked"))
        r_pallas, _ = run_one(mk("pallas"))
        r_dense, snap_dense = run_one(None)

        folds = snap["kernel"]["folds"]
        assert folds["blocksparse:32"]["served"] == 2
        assert folds["dense:16"]["served"] == 2
        assert "kernel" not in snap_dense

        for m, p, d in zip(r_masked, r_pallas, r_dense):
            if m.bucket_len == 16:          # dense-routed: untouched
                np.testing.assert_array_equal(m.coords, d.coords)
                np.testing.assert_array_equal(p.coords, d.coords)
            else:                           # sparse-routed: backends
                np.testing.assert_allclose(  # agree with each other
                    p.coords, m.coords, atol=5e-4)

    def test_sparse_exec_key_actually_served(self, model_and_params):
        """The smoke's routing assertion, in-process: with a sparse
        policy the executor's resident keys include the spec label and
        it took hits (the sparse executable served traffic, not just
        compiled)."""
        # num_global=0: with only 2 blocks at this bucket a global
        # block would make the banded pattern all-live (dense fallback)
        kp = KernelPolicy(table={16: "blocksparse"}, block=8,
                          window=0, num_global=0)
        sched = _scheduler(model_and_params, kernel_policy=kp)
        sched.warmup()
        with sched:
            for r in requests_of((12, 9), key=4):
                assert sched.submit(FoldRequest(
                    seq=r.seq, msa=r.msa)).result(timeout=300).ok
        stats = sched.executor.stats()
        label = kp.spec_for(16).label
        assert any(k[7] == label for k in stats["keys"])
        assert stats["hits"] >= 1

    def test_kernel_policy_none_stats_byte_identical(
            self, model_and_params):
        """The off switch: kernel_policy=None must leave scrubbed
        serve_stats() byte-identical to a scheduler that has never
        heard of kernel selection (same scrub discipline as the mesh/
        recycle/continuous identity pins)."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        def run_one(**kw):
            sched = _scheduler(model_and_params, **kw)
            reqs = requests_of((12, 8), key=9)
            with sched:
                for r in reqs:
                    assert sched.submit(
                        FoldRequest(seq=r.seq, msa=r.msa)).result(
                            timeout=300).ok
            return scrub(sched.serve_stats())

        explicit_off = run_one(kernel_policy=None)
        never_heard = run_one()
        assert json.dumps(explicit_off, sort_keys=True, default=str) \
            == json.dumps(never_heard, sort_keys=True, default=str)
        assert "kernel" not in never_heard


class TestLoadtestFlags:
    def test_kernel_policy_flags_fast(self, tmp_path, capsys):
        """Tier-1 flag-rot tripwire: the --kernel-policy surface drives
        a real (tiny) run and reports the kernel section — per-kernel
        folds/hour, the live-fraction histogram, and the interpret-mode
        numerics check."""
        import sys
        sys.path.insert(0, "tools")
        try:
            import serve_loadtest
        finally:
            sys.path.pop(0)
        rc = serve_loadtest.main([
            "--requests", "8", "--concurrency", "4",
            "--lengths", "12", "--buckets", "16",
            "--msa-depth", str(MSA_DEPTH), "--max-batch", "2",
            "--max-wait-ms", "5", "--num-recycles", "1",
            "--kernel-policy", "blocksparse", "--sparse-block", "8",
            "--sparse-window", "0", "--sparse-global", "0",
            "--dim", "32", "--depth", "1",
            "--metrics-path", str(tmp_path / "m.jsonl")])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip()
                            .splitlines()[-1])
        assert report["served"] == 8
        kern = report["kernel"]
        assert kern["folds"]["blocksparse:16"]["served"] == 8
        assert kern["folds_per_hour_by_kernel"]["blocksparse"] > 0
        assert kern["live_frac_hist"]
        assert kern["numerics_max_diff"]["16"] < 1e-3


class TestContactPriorFlow:
    def test_step_loop_replans_and_relowers(self, model_and_params):
        """contact_priors under a recycle policy: the init pass runs
        the static spec, the mask is re-planned from the batch's own
        recycle-1 distogram, and the remaining recycles run a
        RE-LOWERED step executable (a contact-labeled — or dense —
        step key distinct from the static one), with every request
        still resolving ok and finite."""
        kp = KernelPolicy(table={16: "blocksparse"}, block=8,
                          window=0, num_global=0, contact_priors=True,
                          contact_threshold=0.2)
        reg = MetricsRegistry()
        sched = _scheduler(model_and_params, num_recycles=2,
                           kernel_policy=kp,
                           recycle_policy=RecyclePolicy(preempt=False),
                           registry=reg)
        sched.warmup()
        static_label = kp.spec_for(16).label
        with sched:
            for r in requests_of((12, 9), key=6):
                resp = sched.submit(FoldRequest(
                    seq=r.seq, msa=r.msa)).result(timeout=300)
                assert resp.ok and np.isfinite(resp.coords).all()
        keys = sched.executor.stats()["keys"]
        step_kernels = {k[7] for k in keys if k[6] == "step"}
        # the static step was warmed; the replanned step (contact label
        # or dense fallback) was lowered mid-loop alongside it
        assert static_label in {k[7] for k in keys}
        assert len(step_kernels) >= 2
        folds = sched.serve_stats()["kernel"]["folds"]
        assert any(k.startswith("blocksparse-contact")
                   for k in folds)
