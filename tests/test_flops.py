"""Analytic FLOP model tests (round-4 VERDICT #2): the jaxpr-walking
counter must match closed-form counts on known programs, be invariant to
remat and to which backend kernels are enabled (the property XLA
cost_analysis lacks), and agree with an independent closed-form
derivation of the Evoformer step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.utils.flops import (count_jaxpr_flops,
                                        evoformer_step_flops_formula,
                                        forward_flops, train_step_flops)


class TestCounterPrimitives:
    @pytest.mark.quick
    def test_plain_matmul(self):
        x, w = jnp.ones((8, 16)), jnp.ones((16, 32))
        assert forward_flops(lambda x, w: x @ w, x, w) == 2 * 8 * 16 * 32

    @pytest.mark.quick
    def test_batched_einsum(self):
        a = jnp.ones((4, 8, 16))
        b = jnp.ones((4, 16, 32))
        got = forward_flops(lambda a, b: jnp.einsum("bik,bkj->bij", a, b),
                            a, b)
        assert got == 2 * 4 * 8 * 16 * 32

    @pytest.mark.quick
    def test_scan_multiplies_by_length(self):
        w = jnp.ones((16, 16))

        def f(x):
            return jax.lax.scan(lambda c, _: (c @ w, None), x, None,
                                length=5)[0]

        assert forward_flops(f, jnp.ones((8, 16))) == 5 * 2 * 8 * 16 * 16

    @pytest.mark.quick
    def test_cond_charges_max_branch(self):
        w_small = jnp.ones((16, 8))
        w_big = jnp.ones((16, 64))

        def f(x, pred):
            return jax.lax.cond(pred,
                                lambda x: (x @ w_big).sum(),
                                lambda x: (x @ w_small).sum(), x)

        got = forward_flops(f, jnp.ones((8, 16)), jnp.array(True))
        assert got == 2 * 8 * 16 * 64

    @pytest.mark.quick
    def test_remat_counted_once(self):
        """Forward trace contains each op once — remat recompute is
        excluded by construction (MFU, not HFU)."""
        w = jnp.ones((16, 32))
        plain = forward_flops(lambda x: x @ w, jnp.ones((8, 16)))
        rematd = forward_flops(
            lambda x: jax.checkpoint(lambda y: y @ w)(x), jnp.ones((8, 16)))
        assert plain == rematd == 2 * 8 * 16 * 32

    @pytest.mark.quick
    def test_conv(self):
        x = jnp.ones((1, 8, 16))   # N C W
        k = jnp.ones((4, 8, 3))    # O I W
        f = lambda x, k: jax.lax.conv_general_dilated(
            x, k, (1,), "SAME", dimension_numbers=("NCH", "OIH", "NCH"))
        # out (1, 4, 16): 2 * prod(out) * C_in * kernel_w
        assert forward_flops(f, x, k) == 2 * (1 * 4 * 16) * 8 * 3

    def test_shard_map_counts_all_devices(self):
        from jax.sharding import Mesh, PartitionSpec as P
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
        w = jnp.ones((16, 16))

        def f(x):
            return jax.shard_map(lambda xi: xi @ w, mesh=mesh,
                                 in_specs=P("x"), out_specs=P("x"))(x)

        # per-device (2,16)@(16,16), times 4 devices = global (8,16) work
        assert forward_flops(f, jnp.ones((8, 16))) == 2 * 8 * 16 * 16

    def test_shard_map_excludes_replicated_axes(self):
        """Axes the operands are not sharded over hold replicas; the
        redundant compute is hardware work, not model FLOPs (the MFU
        numerator must not inflate with them)."""
        from jax.sharding import Mesh, PartitionSpec as P
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))
        w = jnp.ones((16, 16))

        def f(x):
            # sharded over 'a' only; the 'b' axis computes replicas
            return jax.shard_map(lambda xi: xi @ w, mesh=mesh,
                                 in_specs=P("a"), out_specs=P("a"))(x)

        assert forward_flops(f, jnp.ones((8, 16))) == 2 * 8 * 16 * 16


class TestModelLevel:
    def _model_batch(self):
        from alphafold2_tpu import Alphafold2
        from alphafold2_tpu.data.synthetic import synthetic_batch
        model = Alphafold2(dim=64, depth=2, heads=4, dim_head=16)
        batch = synthetic_batch(jax.random.PRNGKey(0), batch=1,
                                seq_len=64, msa_depth=5)
        params = model.init(jax.random.PRNGKey(1), batch["seq"],
                            msa=batch["msa"], mask=batch["mask"],
                            msa_mask=batch["msa_mask"])
        return model, params, batch

    def test_matches_closed_form_evoformer(self):
        """Independent derivation (einsum inventory) within 15%."""
        model, params, batch = self._model_batch()
        jaxpr_count = train_step_flops(model, params, batch)
        formula = evoformer_step_flops_formula(64, 2, 64, 5, heads=4,
                                               dim_head=16)
        assert abs(jaxpr_count / formula - 1.0) < 0.15, \
            (jaxpr_count, formula)

    def test_invariant_to_amx_routing(self):
        """The round-4 failure mode: cost_analysis flops changed 10x with
        AMX on/off. The analytic count must be identical."""
        from alphafold2_tpu.ops import cpu_gemm
        model, params, batch = self._model_batch()
        prev = cpu_gemm._enabled
        try:
            cpu_gemm.use_amx_dense(True)
            with_amx = train_step_flops(model, params, batch)
            cpu_gemm.use_amx_dense(False)
            without = train_step_flops(model, params, batch)
        finally:
            cpu_gemm._enabled = prev
        assert with_amx == without > 0

    def test_invariant_to_pallas_routing(self):
        from alphafold2_tpu.ops.attention import (pallas_attention_enabled,
                                                  use_pallas_attention)
        model, params, batch = self._model_batch()
        prev = pallas_attention_enabled()
        try:
            use_pallas_attention(True)
            with_pallas = train_step_flops(model, params, batch)
            use_pallas_attention(False)
            without = train_step_flops(model, params, batch)
        finally:
            use_pallas_attention(prev)
        assert with_pallas == without > 0

    def test_scales_with_depth(self):
        """Trunk dominates: doubling depth should roughly double FLOPs."""
        from alphafold2_tpu import Alphafold2
        from alphafold2_tpu.data.synthetic import synthetic_batch
        batch = synthetic_batch(jax.random.PRNGKey(0), batch=1,
                                seq_len=48, msa_depth=4)

        def flops_at(depth):
            m = Alphafold2(dim=32, depth=depth, heads=2, dim_head=16)
            p = m.init(jax.random.PRNGKey(1), batch["seq"],
                       msa=batch["msa"], mask=batch["mask"],
                       msa_mask=batch["msa_mask"])
            return train_step_flops(m, p, batch)

        f2, f4 = flops_at(2), flops_at(4)
        trunk_ratio = f4 / f2
        assert 1.6 < trunk_ratio < 2.05, trunk_ratio
