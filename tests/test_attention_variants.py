"""Attention-variant tests (README-era menu): linear, memory-compressed,
Kronecker-pooled, block-sparse."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.model.attention_variants import (
    BlockSparseAttention,
    KroneckerAttention,
    LinearAttention,
    MemoryCompressedAttention,
    block_sparse_mask,
    kronecker_pool_2d,
)


def x_mask(key, b=2, n=32, d=16):
    x = jax.random.normal(key, (b, n, d))
    mask = jnp.ones((b, n), dtype=bool).at[:, -8:].set(False)
    return x, mask


class TestLinearAttention:
    def test_shapes_and_finite(self):
        x, mask = x_mask(jax.random.PRNGKey(0))
        mod = LinearAttention(dim=16, heads=2, dim_head=8)
        params = mod.init(jax.random.PRNGKey(1), x, mask=mask)
        out = mod.apply(params, x, mask=mask)
        assert out.shape == x.shape
        assert bool(jnp.isfinite(out).all())

    def test_masked_keys_have_no_influence(self):
        x, mask = x_mask(jax.random.PRNGKey(2))
        mod = LinearAttention(dim=16, heads=2, dim_head=8)
        params = mod.init(jax.random.PRNGKey(3), x, mask=mask)
        out1 = mod.apply(params, x, mask=mask)
        x2 = x.at[:, -8:].add(50.0)  # corrupt masked keys
        out2 = mod.apply(params, x2, mask=mask)
        assert np.allclose(out1[:, :24], out2[:, :24], atol=1e-4)

    def test_cross_attention(self):
        x, _ = x_mask(jax.random.PRNGKey(4), n=8)
        ctx = jax.random.normal(jax.random.PRNGKey(5), (2, 20, 16))
        cmask = jnp.ones((2, 20), dtype=bool)
        mod = LinearAttention(dim=16, heads=2, dim_head=8)
        params = mod.init(jax.random.PRNGKey(6), x, context=ctx,
                          context_mask=cmask)
        out = mod.apply(params, x, context=ctx, context_mask=cmask)
        assert out.shape == x.shape


class TestMemoryCompressed:
    def test_ratios(self):
        for r in (2, 4):
            x, mask = x_mask(jax.random.PRNGKey(7))
            mod = MemoryCompressedAttention(dim=16, heads=2, dim_head=8,
                                            compress_ratio=r)
            params = mod.init(jax.random.PRNGKey(8), x, mask=mask)
            out = mod.apply(params, x, mask=mask)
            assert out.shape == x.shape
            assert bool(jnp.isfinite(out).all())

    def test_non_divisible_length(self):
        x = jax.random.normal(jax.random.PRNGKey(9), (1, 13, 16))
        mod = MemoryCompressedAttention(dim=16, heads=2, dim_head=8,
                                        compress_ratio=4)
        params = mod.init(jax.random.PRNGKey(10), x)
        out = mod.apply(params, x)
        assert out.shape == x.shape
        # unmasked call must equal an explicit all-ones mask (padding must
        # not dilute the last compressed block)
        out_ones = mod.apply(params, x, mask=jnp.ones((1, 13), dtype=bool))
        assert np.allclose(np.asarray(out), np.asarray(out_ones),
                           atol=1e-5)


class TestKronecker:
    def test_pool_axial_tokens(self):
        ctx = jnp.arange(2 * 4 * 6 * 3, dtype=jnp.float32
                         ).reshape(2, 4, 6, 3)
        pooled, token_mask = kronecker_pool_2d(ctx)
        assert pooled.shape == (2, 4 + 6, 3)   # H + W tokens
        assert token_mask.shape == (2, 10)
        assert np.isclose(float(pooled[0, 0, 0]),
                          float(ctx[0, 0, :, 0].mean()))   # row mean
        assert np.isclose(float(pooled[0, 4, 0]),
                          float(ctx[0, :, 0, 0].mean()))   # col mean

    def test_pool_masked(self):
        ctx = jnp.ones((1, 4, 4, 2))
        cmask = jnp.ones((1, 4, 4), dtype=bool).at[:, 2:, :].set(False)
        ctx = ctx.at[:, 2:, :].set(100.0)  # garbage in masked rows
        pooled, token_mask = kronecker_pool_2d(ctx, cmask)
        # valid row tokens unaffected by masked garbage
        assert np.allclose(pooled[0, :2], 1.0)
        # fully-masked rows produce invalid tokens
        assert not bool(token_mask[0, 2]) and not bool(token_mask[0, 3])

    def test_cross_attention(self):
        x = jax.random.normal(jax.random.PRNGKey(11), (2, 8, 16))
        pair = jax.random.normal(jax.random.PRNGKey(12), (2, 8, 8, 16))
        cmask = jnp.ones((2, 8, 8), dtype=bool)
        mod = KroneckerAttention(dim=16, heads=2, dim_head=8)
        params = mod.init(jax.random.PRNGKey(13), x, pair,
                          context_mask=cmask)
        out = mod.apply(params, x, pair, context_mask=cmask)
        assert out.shape == x.shape


class TestBlockSparse:
    @pytest.mark.quick
    def test_mask_pattern(self):
        m = block_sparse_mask(64, block=16, num_global=1, window=1)
        assert m.shape == (64, 64)
        assert bool(m[0, 0])          # diagonal
        assert bool(m[63, 0])         # global block reachable
        assert not bool(m[63, 18])    # far block, not global
        assert bool(m[17, 40])        # within window? 17//16=1, 40//16=2 -> yes
        assert not bool(m[17, 60])    # 1 vs 3 blocks apart

    def test_module(self):
        x, mask = x_mask(jax.random.PRNGKey(14), n=64)
        mod = BlockSparseAttention(dim=16, heads=2, dim_head=8, block=16)
        params = mod.init(jax.random.PRNGKey(15), x, mask=mask)
        out = mod.apply(params, x, mask=mask)
        assert out.shape == x.shape
        assert bool(jnp.isfinite(out).all())

    def test_pallas_path_broadcast_bias(self, monkeypatch):
        # BlockSparseAttention passes a (1, 1, n, n) broadcast bias; the
        # fused path must expand it to the kernel's (b, heads) contract
        # (regression: round-2 review finding)
        import functools

        from alphafold2_tpu.ops import attention as ops_attn

        monkeypatch.setattr(
            ops_attn, "fused_attention",
            functools.partial(ops_attn.fused_attention, interpret=True))
        x, mask = x_mask(jax.random.PRNGKey(16), n=64)
        mod = BlockSparseAttention(dim=16, heads=2, dim_head=8, block=16)
        params = mod.init(jax.random.PRNGKey(17), x, mask=mask)
        ref = mod.apply(params, x, mask=mask)
        with ops_attn.pallas_attention(True):
            out = mod.apply(params, x, mask=mask)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


class TestMultiKernelConv:
    """trRosetta2-style conv blocks (reference README.md:271-340
    `use_conv` / conv_seq_kernels / conv_msa_kernels / dilations)."""

    @pytest.mark.quick
    def test_identity_at_init_and_shapes(self):
        from alphafold2_tpu.model import MultiKernelConvBlock

        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 12, 16))
        blk = MultiKernelConvBlock(dim=16, kernels=((3, 3), (1, 9)),
                                   dilations=(1, 2))
        params = blk.init(jax.random.PRNGKey(1), x)
        out = blk.apply(params, x)
        assert out.shape == x.shape
        # zero-init output projection: the residual branch starts as 0
        assert float(jnp.abs(out).max()) == 0.0

    @pytest.mark.quick
    def test_mask_blocks_leakage(self):
        """Values in masked cells must not influence valid outputs —
        the conv window sees zeros there, not garbage."""
        from conftest import perturb_params

        from alphafold2_tpu.model import MultiKernelConvBlock

        key = jax.random.PRNGKey(2)
        x = jax.random.normal(key, (1, 8, 8, 16))
        mask = jnp.ones((1, 8, 8), bool).at[:, 5:].set(False)
        blk = MultiKernelConvBlock(dim=16, kernels=((3, 3),))
        params = perturb_params(blk.init(jax.random.PRNGKey(3), x, mask),
                                jax.random.PRNGKey(4))
        out1 = blk.apply(params, x, mask)
        x2 = x.at[:, 5:].set(99.0)  # garbage in the masked region
        out2 = blk.apply(params, x2, mask)
        valid = np.asarray(mask)[..., None]
        assert np.allclose(np.asarray(out1) * valid,
                           np.asarray(out2) * valid, atol=1e-6)

    def test_model_use_conv_forward_and_step(self):
        from alphafold2_tpu import Alphafold2
        from alphafold2_tpu.data.synthetic import synthetic_batch
        from alphafold2_tpu.train import TrainState, adam, make_train_step

        model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16,
                           use_conv=True,
                           conv_seq_kernels=((3, 1), (1, 3)),
                           conv_msa_kernels=((1, 3),))
        batch = synthetic_batch(jax.random.PRNGKey(5), batch=1, seq_len=16,
                                msa_depth=3, with_coords=True)
        params = model.init(jax.random.PRNGKey(6), batch["seq"],
                            msa=batch["msa"], mask=batch["mask"],
                            msa_mask=batch["msa_mask"])
        # conv params actually exist in the tree
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        names = ["/".join(str(getattr(k, "key", k)) for k in p)
                 for p, _ in flat]
        assert any("pair_conv" in n for n in names)
        assert any("msa_conv" in n for n in names)

        ret = model.apply(params, batch["seq"], msa=batch["msa"],
                          mask=batch["mask"], msa_mask=batch["msa_mask"])
        assert bool(jnp.isfinite(ret.distance).all())

        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=adam(1e-3), rng=jax.random.PRNGKey(7))
        step = jax.jit(make_train_step(model), donate_argnums=(0,))
        _, metrics = step(state, batch)
        assert bool(jnp.isfinite(metrics["loss"]))
