"""Control-plane actuation tests (ISSUE 16): the pure scaling brain
(`fleet/scaling.py` — burn scale-up, idle scale-down, the hysteresis
dead band, cooldown, quorum, min/max bounds, least-loaded drain
target), the registry heartbeat-TTL sweep (a wedged-but-listening
replica stops owning ring keys), the FeaturePool in-place resize, the
new front-door admin surface (/admin/stats identity block,
/admin/resize, /admin/peers, the fleet_replica_identity single-series
pin), the controller's telemetry helpers (parse_identity,
content_digest, merge_key_profiles, KeyFrequencyLog roundtrip), the
FleetController reconcile cycle against real front doors (join /
leave / sweep / quorum restore / rollout convergence / late-joiner
re-roll / telemetry-driven warming / stale-scrape discard), the
controller-off byte-identity pins, and the obs_fleet decision-log /
identity-check rendering.

Stub-executor + localhost HTTP, no model, no processes — the
test_frontdoor.py convention; serve_smoke.sh phase 15 is the
3-process chaos version of the same story.
"""

import http.server
import importlib.util
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from alphafold2_tpu import fleet
from alphafold2_tpu.fleet.controlplane import (FleetController,
                                               content_digest,
                                               merge_key_profiles,
                                               parse_identity)
from alphafold2_tpu.fleet.frontdoor import FrontDoorServer
from alphafold2_tpu.fleet.registry import ReplicaRegistry
from alphafold2_tpu.fleet.router import ConsistentHashRouter
from alphafold2_tpu.fleet.scaling import (HOLD, SCALE_DOWN, SCALE_UP,
                                          ReplicaSignals, ScalingPolicy,
                                          decide_feature_workers,
                                          decide_scale, drain_target)
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.obs.trace import Tracer
from alphafold2_tpu.serve import (BucketPolicy, FeaturePool, FoldRequest,
                                  Scheduler, SchedulerConfig)
from alphafold2_tpu.serve.metrics import KeyFrequencyLog

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MSA_DEPTH = 3


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


obs_fleet = _load_tool("obs_fleet")


class _OkExecutor:
    def __init__(self):
        self.calls = 0

    def run(self, batch, num_recycles, trace=None):
        self.calls += 1
        b, n = batch["seq"].shape

        class R:
            coords = np.zeros((b, n, 3), np.float32)
            confidence = np.full((b, n), 0.5, np.float32)

        return R()

    def stats(self):
        return {"calls": self.calls}


def _scheduler(model_tag="cp", **kwargs):
    return Scheduler(_OkExecutor(), BucketPolicy((16,)),
                     SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                                     poll_ms=2.0, msa_depth=MSA_DEPTH),
                     model_tag=model_tag,
                     registry=MetricsRegistry(), **kwargs)


def _request(seed=0, n=12, **kwargs):
    rng = np.random.default_rng(seed)
    return FoldRequest(
        seq=rng.integers(0, 20, size=n).astype(np.int32),
        msa=rng.integers(0, 20, size=(MSA_DEPTH, n)).astype(np.int32),
        **kwargs)


def _post(url, payload):
    """(status, decoded body) for an admin POST — keeps the 4xx bodies
    that urllib raises as exceptions."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read().decode("utf-8"))


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode("utf-8")


def _signals(*specs):
    return [ReplicaSignals(**s) for s in specs]


POLICY = ScalingPolicy(min_replicas=1, max_replicas=4,
                       up_burn_rate=1.0, down_burn_rate=0.5,
                       down_idle_fraction=0.80, cooldown_s=30.0)


# -- scaling policy validation -------------------------------------------

@pytest.mark.quick
class TestScalingPolicyValidation:
    def test_defaults_are_valid(self):
        ScalingPolicy()

    def test_min_below_one_rejected(self):
        with pytest.raises(ValueError):
            ScalingPolicy(min_replicas=0)

    def test_max_below_min_rejected(self):
        with pytest.raises(ValueError):
            ScalingPolicy(min_replicas=3, max_replicas=2)

    def test_inverted_hysteresis_band_rejected(self):
        with pytest.raises(ValueError):
            ScalingPolicy(up_burn_rate=0.5, down_burn_rate=1.0)

    def test_inverted_feature_band_rejected(self):
        with pytest.raises(ValueError):
            ScalingPolicy(feature_workers_min=4, feature_workers_max=2)


# -- decide_scale units ---------------------------------------------------

@pytest.mark.quick
class TestDecideScale:
    def test_burn_scale_up(self):
        sigs = _signals({"replica_id": "a", "burn_rate": 0.2},
                        {"replica_id": "b", "burn_rate": 1.5})
        d = decide_scale(POLICY, sigs, now=100.0)
        assert d.action == SCALE_UP
        assert "up_burn_rate" in d.reason
        assert d.fleet_burn == pytest.approx(1.5)

    def test_burn_scale_up_capped_at_max(self):
        sigs = _signals(*({"replica_id": f"r{i}", "burn_rate": 2.0}
                          for i in range(4)))
        d = decide_scale(POLICY, sigs, now=100.0)
        assert d.action == HOLD and "max_replicas" in d.reason

    def test_infinite_burn_reads_as_way_over(self):
        sigs = _signals({"replica_id": "a",
                         "burn_rate": float("inf")})
        d = decide_scale(POLICY, sigs, now=100.0)
        assert d.action == SCALE_UP
        assert d.fleet_burn == pytest.approx(POLICY.up_burn_rate + 1.0)

    def test_featurize_queue_pressure_scale_up(self):
        sigs = _signals({"replica_id": "a", "burn_rate": 0.1,
                         "featurize_queue_depth": 10,
                         "featurize_workers": 2})
        d = decide_scale(POLICY, sigs, now=100.0)
        assert d.action == SCALE_UP and "featurize queue" in d.reason

    def test_idle_scale_down_needs_both_conditions(self):
        # idle enough but burn inside the dead band: HOLD
        sigs = _signals({"replica_id": "a", "burn_rate": 0.6,
                         "idle_fraction": 0.95},
                        {"replica_id": "b", "burn_rate": 0.1,
                         "idle_fraction": 0.95})
        d = decide_scale(POLICY, sigs, now=100.0)
        assert d.action == HOLD and "in band" in d.reason
        # burn low enough but not idle: HOLD
        sigs = _signals({"replica_id": "a", "burn_rate": 0.1,
                         "idle_fraction": 0.5},
                        {"replica_id": "b", "burn_rate": 0.1,
                         "idle_fraction": 0.5})
        assert decide_scale(POLICY, sigs, now=100.0).action == HOLD
        # both: SCALE_DOWN with a drain target
        sigs = _signals({"replica_id": "a", "burn_rate": 0.1,
                         "idle_fraction": 0.95, "queue_depth": 3},
                        {"replica_id": "b", "burn_rate": 0.1,
                         "idle_fraction": 0.95, "queue_depth": 1})
        d = decide_scale(POLICY, sigs, now=100.0)
        assert d.action == SCALE_DOWN
        assert d.drain_target == "b"         # least loaded

    def test_idle_scale_down_refused_at_min(self):
        sigs = _signals({"replica_id": "a", "burn_rate": 0.0,
                         "idle_fraction": 1.0})
        d = decide_scale(POLICY, sigs, now=100.0)
        assert d.action == HOLD and "min_replicas" in d.reason

    def test_hysteresis_band_holds_under_oscillation(self):
        """Burn oscillating anywhere inside (down_burn, up_burn] with
        an idle fleet never actuates in either direction: the dead
        band between the two thresholds absorbs the flapping."""
        for burn in (0.51, 0.6, 0.75, 0.9, 1.0, 0.55, 0.99):
            sigs = _signals({"replica_id": "a", "burn_rate": burn,
                             "idle_fraction": 0.95},
                            {"replica_id": "b", "burn_rate": burn,
                             "idle_fraction": 0.95})
            d = decide_scale(POLICY, sigs, now=100.0)
            assert d.action == HOLD, (burn, d.reason)

    def test_cooldown_suppresses_flapping(self):
        sigs = _signals({"replica_id": "a", "burn_rate": 2.0},
                        {"replica_id": "b", "burn_rate": 2.0})
        d = decide_scale(POLICY, sigs, now=110.0, last_action_s=100.0)
        assert d.action == HOLD and d.reason.startswith("cooldown (")
        # once the cooldown has elapsed, the same signals act
        d = decide_scale(POLICY, sigs, now=131.0, last_action_s=100.0)
        assert d.action == SCALE_UP

    def test_quorum_restore_beats_cooldown(self):
        policy = ScalingPolicy(min_replicas=2, max_replicas=4)
        sigs = _signals({"replica_id": "a"})
        d = decide_scale(policy, sigs, now=100.5, last_action_s=100.0)
        assert d.action == SCALE_UP and "quorum restore" in d.reason

    def test_pending_spawn_counts_toward_quorum(self):
        """The runaway-restore regression: a spawn whose boot spans
        many reconcile intervals satisfies the quorum deficit while it
        warms up — the controller must not spawn again every cycle."""
        policy = ScalingPolicy(min_replicas=3, max_replicas=5)
        sigs = _signals({"replica_id": "a"}, {"replica_id": "b"})
        assert decide_scale(policy, sigs, now=100.0).action == SCALE_UP
        d = decide_scale(policy, sigs, now=100.5, pending=1)
        assert d.action == HOLD and d.pending == 1
        assert "pending" in d.reason
        # two short: one spawn in flight still leaves a deficit
        d = decide_scale(policy, _signals({"replica_id": "a"}),
                         now=100.5, pending=1)
        assert d.action == SCALE_UP

    def test_pending_spawn_holds_tuning_actions(self):
        sigs = _signals({"replica_id": "a", "burn_rate": 5.0})
        d = decide_scale(POLICY, sigs, now=100.0, pending=1)
        assert d.action == HOLD and "pending" in d.reason
        idle = _signals({"replica_id": "a", "idle_fraction": 1.0},
                        {"replica_id": "b", "idle_fraction": 1.0})
        d = decide_scale(POLICY, idle, now=100.0, pending=1)
        assert d.action == HOLD and "pending" in d.reason

    def test_draining_and_unhealthy_do_not_count_toward_quorum(self):
        policy = ScalingPolicy(min_replicas=2, max_replicas=4)
        sigs = _signals({"replica_id": "a"},
                        {"replica_id": "b", "draining": True},
                        {"replica_id": "c", "healthy": False})
        d = decide_scale(policy, sigs, now=100.0)
        assert d.action == SCALE_UP and d.healthy == 1

    def test_drain_target_least_loaded_ordering(self):
        sigs = _signals(
            {"replica_id": "a", "queue_depth": 2},
            {"replica_id": "b", "queue_depth": 1,
             "featurize_queue_depth": 5},
            {"replica_id": "c", "queue_depth": 1,
             "featurize_queue_depth": 2, "served": 9},
            {"replica_id": "d", "queue_depth": 1,
             "featurize_queue_depth": 2, "served": 3})
        assert drain_target(sigs) == "d"     # queue, then featurize,
        #                                      then served tiebreak
        sigs = _signals({"replica_id": "a", "draining": True},
                        {"replica_id": "b", "healthy": False})
        assert drain_target(sigs) is None
        assert drain_target([]) is None


@pytest.mark.quick
class TestDecideFeatureWorkers:
    POLICY = ScalingPolicy(feature_workers_min=1, feature_workers_max=8,
                           feature_queue_per_worker=2.0)

    def test_grow_is_immediate(self):
        s = ReplicaSignals("a", featurize_queue_depth=10,
                           featurize_workers=2)
        assert decide_feature_workers(self.POLICY, s) == 5

    def test_shrink_has_one_worker_hysteresis(self):
        # want = cur - 1: inside the margin, leave it alone
        s = ReplicaSignals("a", featurize_queue_depth=4,
                           featurize_workers=3)
        assert decide_feature_workers(self.POLICY, s) is None
        # want well below: shrink
        s = ReplicaSignals("a", featurize_queue_depth=2,
                           featurize_workers=5)
        assert decide_feature_workers(self.POLICY, s) == 1

    def test_clamped_to_policy_max(self):
        s = ReplicaSignals("a", featurize_queue_depth=100,
                           featurize_workers=2)
        assert decide_feature_workers(self.POLICY, s) == 8

    def test_empty_queue_wants_the_floor(self):
        s = ReplicaSignals("a", featurize_queue_depth=0,
                           featurize_workers=1)
        assert decide_feature_workers(self.POLICY, s) is None


# -- registry heartbeat TTL -----------------------------------------------

@pytest.mark.quick
class TestRegistryTTL:
    def _reg(self, ttl=5.0):
        clk = [100.0]
        reg = ReplicaRegistry(heartbeat_timeout_s=ttl,
                              clock=lambda: clk[0],
                              registry=MetricsRegistry())
        return reg, clk

    def test_sweep_auto_downs_stale_members(self):
        reg, clk = self._reg()
        reg.register("a")
        reg.register("b")
        clk[0] += 6.0
        reg.heartbeat("b")
        epoch_before = reg.epoch
        assert reg.sweep() == ["a"]
        assert reg.epoch == epoch_before + 1   # ONE bump per sweep
        assert not reg.is_healthy("a") and reg.is_healthy("b")
        members = reg.snapshot()["replicas"]
        assert members["a"]["auto_down"] is True
        assert members["b"]["auto_down"] is False

    def test_sweep_bumps_epoch_once_for_many(self):
        reg, clk = self._reg()
        for rid in ("a", "b", "c"):
            reg.register(rid)
        clk[0] += 6.0
        epoch_before = reg.epoch
        assert reg.sweep() == ["a", "b", "c"]
        assert reg.epoch == epoch_before + 1

    def test_heartbeat_revives_auto_downed_not_admin_downed(self):
        reg, clk = self._reg()
        reg.register("a")
        reg.register("b")
        reg.mark("b", up=False)               # administrative pull
        clk[0] += 6.0
        reg.sweep()
        assert not reg.is_healthy("a")
        epoch = reg.epoch
        reg.heartbeat("a")                    # fresh beat: revive
        assert reg.is_healthy("a")
        assert reg.epoch == epoch + 1         # revival rebuilds rings
        reg.heartbeat("b")                    # admin down stays down
        assert not reg.is_healthy("b")

    def test_mark_up_clears_auto_down(self):
        reg, clk = self._reg()
        reg.register("a")
        clk[0] += 6.0
        reg.sweep()
        reg.mark("a", up=True)
        assert reg.is_healthy("a")
        assert reg.snapshot()["replicas"]["a"]["auto_down"] is False

    def test_sweep_noop_without_ttl(self):
        reg = ReplicaRegistry(registry=MetricsRegistry())
        reg.register("a")
        epoch = reg.epoch
        assert reg.sweep() == []
        assert reg.epoch == epoch and reg.is_healthy("a")

    def test_auto_down_counter_minted_only_with_ttl(self):
        mreg = MetricsRegistry()
        ReplicaRegistry(registry=mreg)
        assert "fleet_auto_downs_total" not in mreg.snapshot()
        mreg2 = MetricsRegistry()
        ReplicaRegistry(heartbeat_timeout_s=1.0, registry=mreg2)
        assert "fleet_auto_downs_total" in mreg2.snapshot()

    def test_wedged_but_listening_replica_stops_owning_keys(self):
        """The ISSUE-16 regression: a replica whose TCP accept still
        works but whose heartbeat went stale is swept DOWN with an
        epoch bump, so the hash ring routes its keys elsewhere — it
        stops receiving forwards, not just failing them."""
        reg, clk = self._reg()
        reg.register("a", transport=object())
        reg.register("b", transport=object())
        router = ConsistentHashRouter(reg, self_id="a",
                                      metrics=MetricsRegistry())
        b_keys = [f"k{i}" for i in range(64)
                  if router.owner_for(f"k{i}") == "b"]
        assert b_keys                         # b owns some keyspace
        decision = router.route(b_keys[0])
        assert not decision.is_local and decision.reason == "forward"
        # b wedges: keeps listening (stays registered) but stops
        # heartbeating; a stays fresh
        clk[0] += 6.0
        reg.heartbeat("a")
        assert reg.sweep() == ["b"]
        for key in b_keys:
            assert router.owner_for(key) == "a"
            assert router.route(key).is_local
        # b recovers: one heartbeat re-admits it to the ring
        reg.heartbeat("b")
        assert router.owner_for(b_keys[0]) == "b"


# -- feature-pool resize --------------------------------------------------

@pytest.mark.quick
class TestFeaturePoolResize:
    def test_resize_in_place(self):
        pool = FeaturePool(workers=2, registry=MetricsRegistry())
        try:
            assert pool.resize(5) == 5 and pool.workers == 5
            assert pool.resize(1) == 1 and pool.workers == 1
            assert pool.resizes == 2
        finally:
            pool.stop()

    def test_same_width_is_a_noop(self):
        pool = FeaturePool(workers=3, registry=MetricsRegistry())
        try:
            assert pool.resize(3) == 3
            assert pool.resizes == 0
        finally:
            pool.stop()

    def test_bounds_and_lifecycle_errors(self):
        pool = FeaturePool(workers=2, registry=MetricsRegistry())
        with pytest.raises(ValueError):
            pool.resize(0)
        pool.stop()
        with pytest.raises(RuntimeError):
            pool.resize(3)

    def test_snapshot_resizes_key_only_after_a_resize(self):
        pool = FeaturePool(workers=2, registry=MetricsRegistry())
        try:
            assert "resizes" not in pool.snapshot()   # PR-15 stats pin
            pool.resize(3)
            assert pool.snapshot()["resizes"] == 1
        finally:
            pool.stop()


# -- front-door admin surface ---------------------------------------------

class _Door:
    def __init__(self, rollout=None, model_tag="cp", replica_id="fd0"):
        self.metrics = MetricsRegistry()
        self.scheduler = _scheduler(model_tag=model_tag)
        self.server = FrontDoorServer(self.scheduler, rollout=rollout,
                                      replica_id=replica_id,
                                      metrics=self.metrics)

    def __enter__(self):
        self.scheduler.start()
        self.server.start()
        return self

    def __exit__(self, *exc):
        self.server.stop()
        self.scheduler.stop()


class TestFrontDoorAdmin:
    def test_stats_identity_matches_metrics_series(self):
        with _Door() as d:
            stats = json.loads(_get(d.server.url + "/admin/stats"))
            ident = stats["identity"]
            assert ident["replica_id"] == "fd0"
            assert ident["incarnation"]
            claimed = parse_identity(_get(d.server.url + "/metrics"))
            assert claimed is not None
            assert claimed["replica_id"] == "fd0"
            assert claimed["incarnation"] == ident["incarnation"]

    def test_rollout_moves_identity_one_series_stays_live(self):
        rollout = fleet.RolloutState("v1", registry=MetricsRegistry())
        with _Door(rollout=rollout) as d:
            before = parse_identity(_get(d.server.url + "/metrics"))
            assert before["model_tag"] == "v1"
            status, body = _post(d.server.url + "/admin/rollout",
                                 {"tag": "v2"})
            assert status == 200 and body["tag"] == "v2"
            text = _get(d.server.url + "/metrics")
            after = parse_identity(text)
            # parse_identity returning non-None IS the exactly-one-
            # series-at-1 pin; the superseded tag's series reads 0
            assert after is not None and after["model_tag"] == "v2"
            assert 'model_tag="v1"' in text

    def test_resize_without_pool_is_400(self):
        with _Door() as d:
            status, body = _post(d.server.url + "/admin/resize",
                                 {"workers": 3})
            assert status == 400 and "no feature pool" in body["error"]

    def test_resize_roundtrip_and_errors(self):
        with _Door() as d:
            pool = FeaturePool(workers=2, registry=MetricsRegistry())
            d.scheduler.feature_pool = pool
            try:
                status, body = _post(d.server.url + "/admin/resize",
                                     {"workers": 5})
                assert status == 200
                assert body == {"replica": "fd0", "workers": 5}
                assert pool.workers == 5
                status, body = _post(d.server.url + "/admin/resize",
                                     {"workers": 0})
                assert status == 400      # ValueError surfaces as 400
                status, body = _post(d.server.url + "/admin/resize",
                                     {"wrong": 1})
                assert status == 400 and "bad payload" in body["error"]
            finally:
                d.scheduler.feature_pool = None
                pool.stop()

    def test_peers_requires_wired_admin(self):
        with _Door() as d:
            status, body = _post(
                d.server.url + "/admin/peers",
                {"op": "up", "peer": {"replica_id": "x"}})
            assert status == 400 and "no peer admin" in body["error"]

    def test_peers_dispatch_and_errors(self):
        calls = []
        with _Door() as d:
            def admin(op, peer):
                calls.append((op, peer))
                if op == "down":
                    raise RuntimeError("boom")
                return {"members": 2}

            d.server.peer_admin = admin
            status, body = _post(
                d.server.url + "/admin/peers",
                {"op": "register",
                 "peer": {"replica_id": "r1", "host": "h"}})
            assert status == 200
            assert body == {"members": 2, "op": "register"}
            assert calls[-1] == ("register",
                                 {"replica_id": "r1", "host": "h"})
            status, body = _post(
                d.server.url + "/admin/peers",
                {"op": "reboot", "peer": {}})
            assert status == 400 and "unknown op" in body["error"]
            status, body = _post(
                d.server.url + "/admin/peers",
                {"op": "down", "peer": {"replica_id": "r1"}})
            assert status == 500 and "boom" in body["error"]


# -- telemetry helpers ----------------------------------------------------

@pytest.mark.quick
class TestTelemetryHelpers:
    def test_parse_identity_single_series(self):
        text = ('# HELP fleet_replica_identity x\n'
                'fleet_replica_identity{replica_id="r0",model_tag="v1",'
                'incarnation="abc"} 1\n'
                'fleet_replica_identity{replica_id="r0",model_tag="v0",'
                'incarnation="old"} 0\n')
        ident = parse_identity(text)
        assert ident == {"replica_id": "r0", "model_tag": "v1",
                         "incarnation": "abc"}

    def test_parse_identity_ambiguous_or_absent_is_none(self):
        two = ('fleet_replica_identity{replica_id="r0",'
               'incarnation="a"} 1\n'
               'fleet_replica_identity{replica_id="r0",'
               'incarnation="b"} 1\n')
        assert parse_identity(two) is None
        assert parse_identity("up 1\n") is None
        assert parse_identity(
            'fleet_replica_identity{replica_id="r0"} 0\n') is None

    def test_content_digest_msa_separator(self):
        assert content_digest([1, 2, 3]) == content_digest([1, 2, 3])
        assert content_digest([1, 2, 3]) != content_digest([1, 2, 4])
        assert content_digest([1, 2], [[3]]) != content_digest([1, 2])
        # matches KeyFrequencyLog's aggregation key: same payload, same
        # digest whether it arrives as list or ndarray
        assert content_digest(np.asarray([5, 6], np.int32)) \
            == content_digest([5, 6])
        assert content_digest("not tokens") is None

    def test_merge_key_profiles_sums_across_replicas(self, tmp_path):
        a = tmp_path / "a.keys.jsonl"
        b = tmp_path / "b.keys.jsonl"
        a.write_text(json.dumps({"seq": [1, 2, 3], "count": 4}) + "\n"
                     + json.dumps({"seq": [9, 9], "count": 1}) + "\n")
        b.write_text(json.dumps({"seq": [1, 2, 3], "count": 3}) + "\n"
                     + '{"torn": \n')
        profile = merge_key_profiles([str(a), str(b),
                                      str(tmp_path / "missing.jsonl")])
        assert [(r["seq"], r["count"]) for r in profile] \
            == [([1, 2, 3], 7), ([9, 9], 1)]

    def test_key_frequency_log_roundtrip(self, tmp_path):
        path = str(tmp_path / "keys.jsonl")
        log = KeyFrequencyLog(path, flush_every=3)
        seq = np.asarray([4, 5, 6], np.int32)
        msa = np.asarray([[1, 1, 1]], np.int32)
        log.observe(seq, msa)
        log.observe(seq, msa)
        log.observe(np.asarray([7, 8], np.int32))   # 3rd: auto-flush
        assert os.path.exists(path)
        snap = log.snapshot()
        assert snap["observed"] == 3 and snap["unique"] == 2
        profile = merge_key_profiles([path])
        assert profile[0]["count"] == 2           # hottest first
        assert profile[0]["seq"] == [4, 5, 6]
        assert profile[0]["msa"] == [[1, 1, 1]]
        # the digest the controller dedups by matches the log's key
        assert content_digest(profile[0]["seq"], profile[0]["msa"]) \
            == content_digest(seq, msa)


# -- the reconcile cycle --------------------------------------------------

class _MiniFleet:
    """In-process actuator: real FrontDoorServers over localhost HTTP,
    stub executors, fleet verbs as plain method calls."""

    def __init__(self, tmp_path=None, tag="v1"):
        self.tag = tag
        self.tmp_path = tmp_path
        self.doors = {}                # rid -> _Door
        self.extra_endpoints = {}      # rid -> url (fakes/dead ports)
        self.scale_down_calls = []
        self._next = 0

    def spawn(self):
        rid = f"r{self._next}"
        self._next += 1
        rollout = fleet.RolloutState(self.tag,
                                     registry=MetricsRegistry())
        door = _Door(rollout=rollout, replica_id=rid)
        door.__enter__()
        self.doors[rid] = door
        return rid

    def endpoints(self):
        out = {rid: d.server.url for rid, d in self.doors.items()}
        out.update(self.extra_endpoints)
        return out

    def scale_up(self):
        return self.spawn()

    def scale_down(self, rid):
        self.scale_down_calls.append(rid)
        return self.remove(rid)

    def remove(self, rid):
        door = self.doors.pop(rid, None)
        if door is None:
            return self.extra_endpoints.pop(rid, None) is not None
        door.__exit__()
        return True

    def key_log_paths(self):
        if self.tmp_path is None:
            return {}
        return {rid: os.path.join(str(self.tmp_path),
                                  f"{rid}.keys.jsonl")
                for rid in self.doors}

    def stop(self):
        for rid in list(self.doors):
            self.remove(rid)


def _controller(mini, clk, **kwargs):
    kwargs.setdefault("policy", ScalingPolicy(min_replicas=1,
                                              max_replicas=4,
                                              cooldown_s=5.0))
    kwargs.setdefault("registry", MetricsRegistry())
    kwargs.setdefault("heartbeat_timeout_s", 5.0)
    kwargs.setdefault("probe_timeout_s", 5.0)
    return FleetController(mini, clock=lambda: clk[0], **kwargs)


class _StaleHandler(http.server.BaseHTTPRequestHandler):
    """A replica whose stats and metrics disagree on incarnation — the
    scrape a restart tears in half."""

    def _json(self, obj):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            return self._json({"replica": "stale0", "tag": "",
                               "running": True, "draining": False})
        if self.path == "/admin/stats":
            return self._json({
                "queue_depth": 0, "served": 0,
                "slo": {"classes": {"all": {"latency":
                                            {"burn_rate": 99.0}}}},
                "identity": {"replica_id": "stale0", "model_tag": "",
                             "incarnation": "old"}})
        if self.path == "/metrics":
            body = ('fleet_replica_identity{replica_id="stale0",'
                    'model_tag="",incarnation="new"} 1\n'
                    ).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            return self.wfile.write(body)
        self.send_response(404)
        self.end_headers()

    def log_message(self, *args):
        pass


class TestFleetController:
    def test_join_leave_and_sweep(self, tmp_path):
        mini = _MiniFleet()
        clk = [100.0]
        try:
            r0, r1 = mini.spawn(), mini.spawn()
            ctrl = _controller(mini, clk)
            rec = ctrl.reconcile()
            assert rec["joined"] == [r0, r1]
            assert rec["healthy"] == 2 and rec["left"] == []
            assert rec["decision"]["action"] == HOLD
            rec = ctrl.reconcile()
            assert rec["joined"] == []        # already members
            # r1 wedges: endpoint still listed, but its server is gone
            # (connection refused = failed probe = no heartbeat)
            url = mini.doors[r1].server.url
            mini.doors[r1].__exit__()
            del mini.doors[r1]
            mini.extra_endpoints[r1] = url
            clk[0] += 6.0
            rec = ctrl.reconcile()
            assert rec["swept"] == [r1]
            assert not ctrl.registry.is_healthy(r1)
            assert r1 in ctrl.registry.member_ids()   # down, not gone
            # the endpoint vanishes entirely: unregister
            del mini.extra_endpoints[r1]
            rec = ctrl.reconcile()
            assert rec["left"] == [r1]
            assert r1 not in ctrl.registry.member_ids()
        finally:
            mini.stop()

    def test_quorum_restore_spawns_through_the_actuator(self):
        mini = _MiniFleet()
        clk = [100.0]
        try:
            mini.spawn()
            ctrl = _controller(
                mini, clk,
                policy=ScalingPolicy(min_replicas=2, max_replicas=4,
                                     cooldown_s=5.0))
            rec = ctrl.reconcile()
            assert rec["decision"]["action"] == SCALE_UP
            assert "quorum restore" in rec["decision"]["reason"]
            assert rec["actions"] and \
                rec["actions"][0]["verb"] == "scale_up"
            assert len(mini.doors) == 2
            clk[0] += 1.0
            rec = ctrl.reconcile()           # restored: no more spawns
            assert rec["healthy"] == 2
            assert rec["decision"]["action"] == HOLD
            assert len(mini.doors) == 2
            snap = ctrl.snapshot()
            assert snap["scale_ups"] == 1 and snap["scale_downs"] == 0
        finally:
            mini.stop()

    def test_slow_boot_spawn_is_not_respawned_every_cycle(self):
        """Runaway-restore regression: a replica whose boot spans many
        reconcile intervals (endpoint listed, healthz refusing) counts
        as pending toward quorum; restore only re-fires after the boot
        grace expires."""

        class _SlowBootFleet(_MiniFleet):
            def scale_up(self):
                rid = f"boot{len(self.extra_endpoints)}"
                with socket.socket() as s:
                    s.bind(("127.0.0.1", 0))
                    port = s.getsockname()[1]
                self.extra_endpoints[rid] = f"http://127.0.0.1:{port}"
                return rid

        mini = _SlowBootFleet()
        clk = [100.0]
        try:
            mini.spawn()
            ctrl = _controller(
                mini, clk,
                policy=ScalingPolicy(min_replicas=2, max_replicas=4,
                                     cooldown_s=5.0),
                probe_timeout_s=0.5, boot_grace_s=60.0)
            rec = ctrl.reconcile()
            assert rec["decision"]["action"] == SCALE_UP
            assert len(mini.extra_endpoints) == 1
            # more cycles while the spawn "boots" — inside cooldown the
            # hold is the cooldown's, past it the pending spawn alone
            # must keep restore quiet: either way, no more spawns
            for step, want in ((0.5, "cooldown"), (6.0, "pending"),
                               (6.0, "pending")):
                clk[0] += step
                rec = ctrl.reconcile()
                assert rec["decision"]["action"] == HOLD
                assert rec["pending"] == list(mini.extra_endpoints)
                assert want in rec["decision"]["reason"]
            assert len(mini.extra_endpoints) == 1
            assert ctrl.snapshot()["scale_ups"] == 1
            # the boot grace expires without a join: restore re-fires
            clk[0] += 61.0
            rec = ctrl.reconcile()
            assert rec["decision"]["action"] == SCALE_UP
            assert "quorum restore" in rec["decision"]["reason"]
            assert len(mini.extra_endpoints) == 2
        finally:
            mini.stop()

    def test_stale_scrape_contributes_neutral_signals(self):
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                              _StaleHandler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        mini = _MiniFleet()
        clk = [100.0]
        try:
            mini.extra_endpoints["stale0"] = \
                f"http://127.0.0.1:{srv.server_address[1]}"
            ctrl = _controller(mini, clk)
            rec = ctrl.reconcile()
            assert rec["stale_scrapes"] == 1
            sig = rec["signals"][0]
            # burn 99 was in the stats body — discarded, not acted on
            assert sig["burn"] == 0.0 and sig["idle"] == 0.0
            assert rec["decision"]["action"] == HOLD
            assert len(mini.doors) == 0      # nothing spawned
        finally:
            mini.stop()
            srv.shutdown()
            srv.server_close()

    def test_resize_actuation_end_to_end(self):
        mini = _MiniFleet()
        clk = [100.0]
        pool = FeaturePool(workers=2, registry=MetricsRegistry())
        try:
            rid = mini.spawn()
            mini.doors[rid].scheduler.feature_pool = pool
            ctrl = _controller(
                mini, clk,
                policy=ScalingPolicy(feature_queue_per_worker=2.0))
            sig = ReplicaSignals(rid, healthy=True, incarnation="x",
                                 featurize_queue_depth=10,
                                 featurize_workers=2)
            out = ctrl._actuate_resize(mini.endpoints(), [sig])
            assert out == {rid: 5} and pool.workers == 5
            # stale (no incarnation) and draining replicas are skipped
            assert ctrl._actuate_resize(
                mini.endpoints(),
                [ReplicaSignals(rid, featurize_queue_depth=50,
                                featurize_workers=1)]) == {}
            assert ctrl._actuate_resize(
                mini.endpoints(),
                [ReplicaSignals(rid, incarnation="x", draining=True,
                                featurize_queue_depth=50,
                                featurize_workers=1)]) == {}
        finally:
            mini.doors[rid].scheduler.feature_pool = None
            pool.stop()
            mini.stop()

    def test_rollout_converges_and_rolls_late_joiners(self):
        mini = _MiniFleet(tag="v1")
        clk = [100.0]
        try:
            mini.spawn(), mini.spawn()
            ctrl = _controller(mini, clk, rollout_attempts=2,
                               rollout_backoff_s=0.01)
            ctrl.reconcile()
            report = ctrl.rollout("v2")
            assert report["converged"] and report["stragglers"] == []
            assert sorted(report["epochs"]) == sorted(mini.doors)
            for d in mini.doors.values():
                hz = json.loads(_get(d.server.url + "/healthz"))
                assert hz["tag"] == "v2"
            # a late joiner boots on v1; the next cycle re-rolls it
            late = mini.spawn()
            rec = ctrl.reconcile()
            assert rec["rollout_target"] == "v2"
            assert rec["rollout_stragglers"] == [late]
            clk[0] += 1.0
            rec = ctrl.reconcile()
            assert rec["rollout_stragglers"] == []
            hz = json.loads(_get(mini.doors[late].server.url
                                 + "/healthz"))
            assert hz["tag"] == "v2"
        finally:
            mini.stop()

    def test_rollout_reports_unreachable_stragglers(self):
        mini = _MiniFleet()
        clk = [100.0]
        try:
            mini.spawn()
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                dead_port = s.getsockname()[1]
            mini.extra_endpoints["dead0"] = \
                f"http://127.0.0.1:{dead_port}"
            ctrl = _controller(mini, clk, rollout_attempts=2,
                               rollout_backoff_s=0.01,
                               probe_timeout_s=0.5)
            report = ctrl.rollout("v2")
            assert not report["converged"]
            assert report["stragglers"] == ["dead0"]
            assert report["epochs"]["dead0"] is None
        finally:
            mini.stop()

    def test_warm_from_telemetry_dedups(self, tmp_path):
        mini = _MiniFleet(tmp_path=tmp_path)
        clk = [100.0]
        try:
            rid = mini.spawn()
            # the replica's served-key telemetry: one hot key over the
            # min count, one cold key under it
            log = KeyFrequencyLog(mini.key_log_paths()[rid],
                                  flush_every=1)
            hot = np.asarray(list(range(12)), np.int32)
            log.observe(hot)
            log.observe(hot)
            log.observe(np.asarray([1] * 12, np.int32))
            ctrl = _controller(mini, clk, warm=True, warm_top_k=4,
                               warm_min_count=2)
            rec = ctrl.reconcile()
            assert rec["warm_submissions"] == 1
            assert len(ctrl._warmed) == 1
            clk[0] += 1.0
            rec = ctrl.reconcile()           # same head: dedup holds
            assert rec["warm_submissions"] == 0
            # the warm fold actually lands: wait for the ticket
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if all(t.done() for t in ctrl._warm_tickets):
                    break
                time.sleep(0.05)
            resp = ctrl._warm_tickets[0].result(timeout=30)
            assert resp.ok
            assert resp.request_id.startswith("warm-")
            assert ctrl.snapshot()["warmed"] == 1
        finally:
            mini.stop()

    def test_decisions_jsonl_and_reconcile_trace(self, tmp_path):
        mini = _MiniFleet()
        clk = [100.0]
        decisions_path = str(tmp_path / "controller.decisions.jsonl")
        trace_path = str(tmp_path / "controller-traces.jsonl")
        tracer = Tracer(jsonl_path=trace_path, origin="controller")
        try:
            mini.spawn()
            ctrl = _controller(mini, clk,
                               decisions_path=decisions_path,
                               tracer=tracer)
            ctrl.reconcile()
            ctrl.reconcile()
            with open(decisions_path) as fh:
                records = [json.loads(line) for line in fh]
            assert [r["event"] for r in records] == ["reconcile"] * 2
            assert [r["reconcile"] for r in records] == [1, 2]
            assert records[0]["signals"] and records[0]["decision"]
            tracer.close()
            with open(trace_path) as fh:
                traces = [json.loads(line) for line in fh]
            assert len(traces) == 2
            assert traces[0]["origin"] == "controller"
            assert [s["name"] for s in traces[0]["spans"]] \
                == ["reconcile"]
        finally:
            mini.stop()

    def test_loop_survives_reconcile_errors(self):
        class _Broken:
            def endpoints(self):
                raise RuntimeError("actuator detonated")

        ctrl = FleetController(_Broken(), interval_s=0.01,
                               registry=MetricsRegistry())
        ctrl.start()
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                with ctrl._lock:
                    errors = [d for d in ctrl.decisions
                              if d.get("event") == "reconcile_error"]
                if len(errors) >= 2:     # it kept cycling past a crash
                    break
                time.sleep(0.01)
            assert len(errors) >= 2
            assert "actuator detonated" in errors[0]["error"]
        finally:
            ctrl.stop()


# -- controller-off byte-identity ----------------------------------------

@pytest.mark.quick
class TestOffIdentity:
    def test_scheduler_without_key_log_stats_unchanged(self):
        sched = _scheduler()
        with sched:
            assert sched.submit(_request(seed=1)).result(timeout=60).ok
            stats = sched.serve_stats()
        assert "key_log" not in stats
        # ... and arming it mints exactly the one new key
        sched2 = _scheduler(key_log=KeyFrequencyLog(
            os.path.join("/tmp", f"cp-keys-{os.getpid()}.jsonl"),
            flush_every=10**6))
        with sched2:
            assert sched2.submit(_request(seed=1)).result(
                timeout=60).ok
            stats2 = sched2.serve_stats()
        assert stats2["key_log"]["observed"] == 1
        assert set(stats2) - set(stats) == {"key_log"}

    def test_no_controller_metric_names_without_a_controller(self):
        reg = MetricsRegistry()
        sched = Scheduler(_OkExecutor(), BucketPolicy((16,)),
                          SchedulerConfig(max_batch_size=2,
                                          max_wait_ms=10.0, poll_ms=2.0,
                                          msa_depth=MSA_DEPTH),
                          model_tag="cp", registry=reg)
        server = FrontDoorServer(sched, replica_id="fd0", metrics=reg)
        sched.start()
        server.start()
        try:
            names = set(reg.snapshot())
        finally:
            server.stop()
            sched.stop()
        assert not {n for n in names if n.startswith("controller_")}
        assert "fleet_auto_downs_total" not in names
        # a controller on the same registry mints them
        reg2 = MetricsRegistry()
        FleetController(_MiniFleet(), registry=reg2)
        names2 = set(reg2.snapshot())
        assert "controller_reconciles_total" in names2
        assert "fleet_auto_downs_total" in names2   # TTL registry

    def test_registry_without_ttl_snapshot_unchanged(self):
        reg = ReplicaRegistry(registry=MetricsRegistry())
        reg.register("a")
        assert "auto_down" not in reg.snapshot()["replicas"]["a"]
        ttl = ReplicaRegistry(heartbeat_timeout_s=5.0,
                              registry=MetricsRegistry())
        ttl.register("a")
        assert "auto_down" in ttl.snapshot()["replicas"]["a"]


# -- obs_fleet rendering --------------------------------------------------

@pytest.mark.quick
class TestObsFleetControlPlane:
    def test_classify_jsonl(self):
        assert obs_fleet._classify_jsonl("keys.jsonl") == "keys"
        assert obs_fleet._classify_jsonl("r0.keys.jsonl") == "keys"
        assert obs_fleet._classify_jsonl(
            "controller.decisions.jsonl") == "decisions"
        assert obs_fleet._classify_jsonl("traces.jsonl") == "trace"

    def test_gather_paths_routes_by_kind(self, tmp_path):
        (tmp_path / "traces.jsonl").write_text("{}\n")
        (tmp_path / "keys.jsonl").write_text("{}\n")
        (tmp_path / "controller.decisions.jsonl").write_text("{}\n")
        (tmp_path / "m.prom").write_text("up 1\n")
        traces, proms, decisions, keys = obs_fleet.gather_paths(
            [str(tmp_path)])
        assert [os.path.basename(p) for p in traces] == ["traces.jsonl"]
        assert [os.path.basename(p) for p in proms] == ["m.prom"]
        assert [os.path.basename(p) for p in decisions] \
            == ["controller.decisions.jsonl"]
        assert [os.path.basename(p) for p in keys] == ["keys.jsonl"]

    def test_load_decisions_flags_torn_lines(self, tmp_path):
        p = tmp_path / "d.decisions.jsonl"
        p.write_text(json.dumps({"event": "reconcile",
                                 "reconcile": 1}) + "\n"
                     + '{"torn\n'
                     + json.dumps({"no_event": True}) + "\n")
        records, problems = obs_fleet.load_decisions([str(p)])
        assert len(records) == 1 and records[0]["reconcile"] == 1
        assert len(problems) == 2

    def test_controller_summary(self):
        decisions = [
            {"event": "reconcile", "reconcile": 1, "healthy": 2,
             "endpoints": ["r0", "r1"], "joined": ["r0", "r1"],
             "decision": {"reason": "quorum"}, "stale_scrapes": 1,
             "actions": [{"verb": "scale_up", "replica": "r2"}],
             "resized": {"r0": 4}, "warm_submissions": 2},
            {"event": "reconcile_error", "error": "x"},
            {"event": "rollout", "tag": "v2", "converged": True,
             "stragglers": []},
        ]
        s = obs_fleet.controller_summary(decisions)
        assert s["reconciles"] == 1 and s["errors"] == 1
        assert s["actions"] == [{"reconcile": 1, "verb": "scale_up",
                                 "replica": "r2", "error": None,
                                 "reason": "quorum"}]
        assert s["joined"] == ["r0", "r1"]
        assert s["stale_scrapes"] == 1 and s["resizes"] == 1
        assert s["warm_submissions"] == 2
        assert s["rollouts"] == [{"tag": "v2", "converged": True,
                                  "stragglers": []}]
        assert s["replicas_over_time"] == [{"reconcile": 1,
                                            "healthy": 2,
                                            "endpoints": 2}]

    def test_check_identity_pins_and_conflicts(self):
        good = ('fleet_replica_identity{replica_id="r0",model_tag="v1",'
                'incarnation="a"} 1\n')
        assert obs_fleet.check_identity({"s0.prom": good}) == []
        # two series at 1 in one exposition
        two = good + ('fleet_replica_identity{replica_id="r0",'
                      'model_tag="v1",incarnation="b"} 1\n')
        problems = obs_fleet.check_identity({"s0.prom": two})
        assert len(problems) == 1 and "2" in problems[0]
        # same replica_id, two incarnations across sources
        other = ('fleet_replica_identity{replica_id="r0",'
                 'model_tag="v1",incarnation="b"} 1\n')
        problems = obs_fleet.check_identity({"s0.prom": good,
                                             "s1.prom": other})
        assert len(problems) == 1
        assert "stale scrape hazard" in problems[0]
        # expositions without the metric are exempt (pre-fleet runs)
        assert obs_fleet.check_identity({"s0.prom": "up 1\n"}) == []
