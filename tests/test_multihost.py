"""Multi-host backend test: a REAL 2-process jax cluster on CPU
(`jax.distributed.initialize` + cross-process global arrays + a
collective), exercising parallel/multihost.py the way a pod entrypoint
does — the reference's NCCL/DeepSpeed story is empty stubs, so this is
the distributed-backend evidence (SURVEY.md §5.8).

Spawned as subprocesses because a cluster cannot share this pytest
process's already-initialized single-process backend.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _scrubbed_env():
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)      # no axon plugin injection
    env.pop("JAX_PLATFORMS", None)   # child sets its own
    env.pop("XLA_FLAGS", None)
    return env


class TestTwoProcessCluster:
    def test_global_array_and_cross_host_reduction(self):
        n = 2
        addr = f"localhost:{_free_port()}"
        procs = [
            subprocess.Popen(
                [sys.executable, _CHILD, str(i), str(n), addr],
                env=_scrubbed_env(), cwd=_REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for i in range(n)
        ]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=180)
                outs.append((p.returncode, out, err))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for rc, out, err in outs:
            assert rc == 0, f"child failed (rc={rc}):\n{err[-2000:]}"
        # sum(arange(32)) — every host must see the global total
        for rc, out, err in outs:
            assert "SUM 496.0" in out, (out, err[-500:])


def test_initialize_noop_single_process():
    """initialize() with no coordinator info is a documented no-op (local
    runs and tests) — it must not touch the existing backend."""
    from alphafold2_tpu.parallel import multihost

    assert multihost.initialize() is False


@pytest.mark.quick
def test_package_import_does_not_initialize_backend():
    """The pod contract: `import alphafold2_tpu` then
    multihost.initialize() must work, so the package import may not
    initialize an XLA backend. Checked in a clean subprocess (this
    pytest process initialized its backend long ago)."""
    code = (
        "from jax._src import xla_bridge\n"
        "import alphafold2_tpu\n"
        "import alphafold2_tpu.parallel.multihost\n"
        "import alphafold2_tpu.data, alphafold2_tpu.config\n"
        "assert not xla_bridge.backends_are_initialized()\n"
        "print('import-clean')\n")
    env = _scrubbed_env()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=_REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "import-clean" in proc.stdout
