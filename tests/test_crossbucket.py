"""Cross-bucket continuous batching tests (ISSUE 13): the
AdmissionPricer's priced trade (pad-frac guard, deadline tiebreak,
native-imminent refusal, extension pricing), cross-bucket admitted-row
numerics byte-equal to folding the same request alone at the HOST shape
(single-chip and on a 1x2 mesh lease), the HBM host-shape re-price
falling back to native-bucket formation, admission-aware eager batch
formation, the cross_bucket=False scrubbed-stats identity pin,
padding-as-dead-blocks contact planning, and the loadtest
--cross-bucket/--eager-form flag surface."""

import json
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_requests
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (AdmissionPricer, BucketPolicy,
                                  FoldExecutor, FoldMemoryModel,
                                  FoldRequest, MeshPolicy, RecyclePolicy,
                                  Scheduler, SchedulerConfig,
                                  ServeMetrics)

MSA_DEPTH = 3


@pytest.fixture(scope="module")
def model_and_params():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                      predict_coords=True, structure_module_depth=1)
    n = 16
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
        mask=jnp.ones((1, n), bool),
        msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
    return model, params


def requests_of(lengths, key=1):
    return synthetic_requests(jax.random.PRNGKey(key),
                              num=len(lengths), lengths=lengths,
                              msa_depth=MSA_DEPTH)


class TestAdmissionPricer:
    def price(self, pricer, **kw):
        base = dict(native_len=16, host_len=32, length=12,
                    batch_size=4, msa_depth=3, candidate_steps=3,
                    remaining_host_steps=3, native_delay_s=1.0,
                    deadline_slack_s=None, host_step_s=0.1)
        base.update(kw)
        return pricer.price(**base)

    def test_step_cost_monotone_in_length(self):
        p = AdmissionPricer()
        assert p.step_cost(32, 4, 3) > p.step_cost(16, 4, 3) \
            > p.step_cost(8, 4, 3)

    def test_pad_frac_guard_refuses(self):
        p = AdmissionPricer(max_pad_frac=0.5)
        d = self.price(p, length=12)            # 1 - 12/32 = 0.625
        assert not d.admit and d.reason == "pad_frac"
        assert d.pad_frac == pytest.approx(0.625)
        # even a deadline about to die cannot override the hard guard
        d = self.price(p, length=12, deadline_slack_s=0.0)
        assert not d.admit and d.reason == "pad_frac"

    def test_deadline_tiebreak_admits_despite_cost(self):
        p = AdmissionPricer(max_pad_frac=0.75)
        # extension 3 at a huge step time would normally refuse...
        d = self.price(p, remaining_host_steps=0, host_step_s=100.0,
                       native_delay_s=0.5)
        assert not d.admit and d.reason == "padded_cost"
        # ...but a candidate that would MISS its deadline waiting for
        # the native bucket admits regardless
        d = self.price(p, remaining_host_steps=0, host_step_s=100.0,
                       native_delay_s=0.5, deadline_slack_s=0.1)
        assert d.admit and d.reason == "deadline"

    def test_native_imminent_refuses(self):
        p = AdmissionPricer()
        d = self.price(p, native_delay_s=0.0)
        assert not d.admit and d.reason == "native_imminent"

    def test_free_ride_admits_and_extension_prices(self):
        p = AdmissionPricer()
        # candidate fits inside the remaining host steps: zero excess
        d = self.price(p, candidate_steps=3, remaining_host_steps=3,
                       native_delay_s=0.01, host_step_s=10.0)
        assert d.admit and d.reason == "priced"
        assert d.excess_s == 0.0
        # extension beyond the loop is priced against the delay
        d = self.price(p, candidate_steps=3, remaining_host_steps=0,
                       native_delay_s=0.01, host_step_s=10.0)
        assert not d.admit and d.reason == "padded_cost"
        assert d.excess_s > d.native_delay_s

    def test_unmeasured_step_time_leans_toward_admitting(self):
        # before the first EWMA sample host_step_s is 0: extension is
        # priced free, so a cold loop admits whenever the native
        # bucket is not imminent
        p = AdmissionPricer()
        d = self.price(p, remaining_host_steps=0, host_step_s=0.0,
                       native_delay_s=0.001)
        assert d.admit and d.reason == "priced"


class GatedInitExecutor(FoldExecutor):
    """Real executor whose FIRST armed run_init blocks until released:
    the deterministic window for submitting work that must be admitted
    MID-LOOP rather than riding the founder batch."""

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.reached = threading.Event()
        self.release = threading.Event()
        self.armed = False

    def run_init(self, *a, **k):
        out = super().run_init(*a, **k)
        if self.armed:
            self.armed = False
            self.reached.set()
            assert self.release.wait(timeout=120)
        return out


def _scheduler(model_and_params, policy=None, num_recycles=3,
               buckets=(8, 16), max_batch=2, ex_cls=FoldExecutor, **kw):
    kw.setdefault("metrics", ServeMetrics(registry=MetricsRegistry()))
    kw.setdefault("registry", MetricsRegistry())
    ex = ex_cls(*model_and_params, max_entries=16)
    sched = Scheduler(
        ex, BucketPolicy(buckets),
        SchedulerConfig(max_batch_size=max_batch, max_wait_ms=5.0,
                        num_recycles=num_recycles, msa_depth=MSA_DEPTH),
        recycle_policy=policy, **kw)
    return ex, sched


XB = dict(converge_tol=0.0, continuous=True, cross_bucket=True,
          preempt=False)


class TestCrossBucketByteEqual:
    def test_admitted_short_byte_equal_alone_at_host_shape(
            self, model_and_params):
        """ISSUE 13 acceptance, single chip: a SHORT request admitted
        into a longer host batch's freed row mid-loop serves final
        coords BYTE-equal to the same request folded alone at the HOST
        shape, retires against its own age (full depth), and reports
        its NATIVE bucket."""
        founder = requests_of((12,), key=5)[0]     # bucket 16 (host)
        short = requests_of((7,), key=6)[0]        # bucket 8 (native)
        ex, sched = _scheduler(model_and_params, RecyclePolicy(**XB),
                               ex_cls=GatedInitExecutor)
        sched.warmup()
        ex.armed = True
        sched.start()
        try:
            tf = sched.submit(FoldRequest(seq=founder.seq,
                                          msa=founder.msa))
            assert ex.reached.wait(timeout=300)
            ts = sched.submit(FoldRequest(seq=short.seq, msa=short.msa))
            time.sleep(0.1)       # let the short reach pending
            ex.release.set()
            rf = tf.result(timeout=300)
            rs = ts.result(timeout=300)
        finally:
            sched.stop()
        assert rf.ok and rs.ok, (rf.error, rs.error)
        assert rs.recycles == 3            # its OWN age, full depth
        assert rs.bucket_len == 8          # native-bucket attribution
        rec = sched.serve_stats()["recycle"]
        assert rec["cross_bucket_admissions"] == 1
        assert rec["row_admissions"] == 1
        # pad-fraction observability: one admit at 1 - 7/16
        snap = sched.metrics.snapshot()
        assert snap["row_admits"] == 1
        assert snap["admit_pad_fraction"]["count"] == 1
        assert snap["admit_pad_fraction"]["p50"] == \
            pytest.approx(1.0 - 7.0 / 16.0)
        assert snap["padding_waste_admitted"] > 0.0
        # byte-equality against the same request folded ALONE AT THE
        # HOST SHAPE: a bucket policy with only the host edge maps the
        # short request onto it
        _, alone = _scheduler(model_and_params,
                              RecyclePolicy(converge_tol=0.0),
                              buckets=(16,))
        with alone:
            rs2 = alone.submit(FoldRequest(seq=short.seq,
                                           msa=short.msa)).result(
                                               timeout=300)
        np.testing.assert_array_equal(rs.coords, rs2.coords)
        np.testing.assert_array_equal(rs.confidence, rs2.confidence)

    @pytest.mark.skipif(len(jax.devices()) < 2,
                        reason="needs >= 2 devices")
    def test_cross_admission_on_mesh_lease_byte_equal(
            self, model_and_params):
        """Cross-bucket admission from a dispatch-pool thread on a
        1x2 mesh lease: the short rides the leased host loop in place
        (no repack of the mesh-sharded carry) and its result is
        byte-equal to folding it alone at the host shape on the same
        mesh."""
        founder = requests_of((12,), key=5)[0]
        short = requests_of((7,), key=6)[0]

        def mk(gated, buckets, shapes):
            ex, sched = _scheduler(
                model_and_params,
                RecyclePolicy(**XB), buckets=buckets,
                ex_cls=GatedInitExecutor if gated else FoldExecutor,
                mesh_policy=MeshPolicy(shapes,
                                       devices=jax.devices()[:2]))
            return ex, sched

        # ONE 2-chip slice shared by both buckets: while the host loop
        # leases it, the short's native bucket has no free slice —
        # exactly the starved-slice regime cross-bucket serves
        ex, sched = mk(True, (8, 16), {8: 2, 16: 2})
        sched.warmup()
        ex.armed = True
        sched.start()
        try:
            tf = sched.submit(FoldRequest(seq=founder.seq,
                                          msa=founder.msa))
            assert ex.reached.wait(timeout=300)
            ts = sched.submit(FoldRequest(seq=short.seq, msa=short.msa))
            time.sleep(0.1)
            ex.release.set()
            rf = tf.result(timeout=300)
            rs = ts.result(timeout=300)
        finally:
            sched.stop()
        assert rf.ok and rs.ok, (rf.error, rs.error)
        stats = sched.serve_stats()
        assert stats["recycle"]["cross_bucket_admissions"] == 1
        assert "1x2" in stats["mesh"]["folds"]       # ran sharded
        _, alone = mk(False, (16,), {16: 2})
        alone.warmup()
        with alone:
            rs2 = alone.submit(FoldRequest(seq=short.seq,
                                           msa=short.msa)).result(
                                               timeout=300)
        np.testing.assert_array_equal(rs.coords, rs2.coords)


class _ContStub:
    """Step/admission-capable executor stub with deterministic per-row
    convergence keyed by the seq's first token (see
    tests/test_continuous.py, whose stub this mirrors + span_attrs on
    run_init_rows for the cross-bucket native_bucket tagging)."""

    def __init__(self, plan):
        self.plan = plan
        self.calls = []
        self.reached = threading.Event()
        self.release = threading.Event()
        self.gate_at = None
        self._lock = threading.Lock()

    def _mk_state(self, ids, counts, b, n):
        coords = np.zeros((b, n, 3), np.float32)
        for i, c in enumerate(counts):
            coords[i] = float(c)
        return SimpleNamespace(coords=coords,
                               confidence=np.zeros((b, n), np.float32),
                               recyclables=None,
                               ids=np.array(ids), counts=np.array(counts))

    def run_init(self, batch, trace=None, devices=None,
                 mesh_shape=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        ids = seq[:, 0]
        with self._lock:
            self.calls.append(("init", [int(i) for i in ids]))
        return self._mk_state(ids, [0] * b, b, n)

    def run_init_rows(self, batch, state, row_mask, trace=None,
                      devices=None, mesh_shape=None, span_attrs=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        mask = np.asarray(row_mask)
        ids = state.ids.copy()
        counts = state.counts.copy()
        ids[mask] = seq[:, 0][mask]
        counts[mask] = 0
        with self._lock:
            self.calls.append(
                ("init_rows", [int(i) for i in seq[:, 0][mask]]))
        return self._mk_state(ids, counts, b, n)

    def run_step(self, batch, state, recycle_index, trace=None,
                 devices=None, mesh_shape=None, span_attrs=None):
        b, n = np.asarray(batch["seq"]).shape
        with self._lock:
            self.calls.append(("step", int(recycle_index)))
            gated = self.gate_at is not None \
                and recycle_index == self.gate_at
            if gated:
                self.gate_at = None
        if gated:
            self.reached.set()
            assert self.release.wait(timeout=60)
        counts = [min(int(c) + 1,
                      self.plan.get(int(t), 10 ** 9))
                  for t, c in zip(state.ids, state.counts)]
        time.sleep(0.01)
        return self._mk_state(state.ids, counts, b, n)

    def run(self, batch, num_recycles, **kw):
        st = self.run_init(batch)
        return SimpleNamespace(coords=st.coords,
                               confidence=st.confidence)

    def stats(self):
        return {"calls": len(self.calls)}


def _stub_sched(stub, num_recycles, policy, max_batch=2,
                buckets=(16, 32), **kw):
    kw.setdefault("metrics", ServeMetrics(registry=MetricsRegistry()))
    kw.setdefault("registry", MetricsRegistry())
    return Scheduler(
        stub, BucketPolicy(buckets),
        SchedulerConfig(max_batch_size=max_batch, max_wait_ms=5.0,
                        num_recycles=num_recycles, msa_depth=0),
        recycle_policy=policy, **kw)


def _req(token, length=28, **kw):
    return FoldRequest(seq=np.full(length, token, np.int32), **kw)


class TestCrossBucketScheduling:
    def test_hbm_refusal_falls_back_to_native_wait(self):
        """A cross-bucket candidate the (tightened) HBM guard refuses
        AT THE HOST SHAPE is not admitted — it returns to its NATIVE
        pending queue and folds through normal batch formation at its
        own bucket once the loop drains."""
        mem = FoldMemoryModel(param_bytes=0, dim=64, heads=4)
        mem.hbm_bytes_per_device = 1 << 60       # admits everything
        pol = MeshPolicy({16: 1, 32: 1}, devices=jax.devices()[:1],
                         memory=mem)
        stub = _ContStub({1: 10 ** 9})           # founder never converges
        stub.gate_at = 1
        sched = _stub_sched(
            stub, 3,
            RecyclePolicy(converge_tol=0.5, **{k: v for k, v in
                          XB.items() if k != "converge_tol"}),
            mesh_policy=pol)
        sched.start()
        try:
            t1 = sched.submit(_req(1, length=28))    # host bucket 32
            assert stub.reached.wait(timeout=60)
            t2 = sched.submit(_req(2, length=12))    # native bucket 16
            time.sleep(0.05)
            mem.hbm_bytes_per_device = 1             # guard tightens
            stub.release.set()
            r1 = t1.result(timeout=60)
            r2 = t2.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r2.ok
        rec = sched.serve_stats()["recycle"]
        assert rec["cross_bucket_admissions"] == 0
        assert rec["row_admissions"] == 0
        # the candidate folded in its own native batch afterwards
        assert r2.bucket_len == 16 and r2.recycles == 3
        assert ("init", [2, 2]) in stub.calls or \
            ("init", [2]) in [(c[0], c[1][:1]) for c in stub.calls
                              if c[0] == "init"]

    def test_refused_candidate_reenables_worker_yield(self):
        """A pricer refusal marks the entry cross_refused, so the
        inline admission gate yields the worker on its next gap and
        the refusal's fallback — drain + native formation — actually
        happens instead of the entry starving behind a refilled
        loop."""
        stub = _ContStub({1: 10 ** 9})
        stub.gate_at = 1
        # max_pad_frac too tight for a 12-residue fold at host 32:
        # the pricer refuses on pad_frac every time
        policy = RecyclePolicy(converge_tol=0.5, continuous=True,
                               cross_bucket=True,
                               cross_bucket_max_pad_frac=0.5,
                               preempt=False)
        sched = _stub_sched(stub, 6, policy)
        sched.start()
        try:
            t1 = sched.submit(_req(1, length=28))
            assert stub.reached.wait(timeout=60)
            t2 = sched.submit(_req(2, length=12))    # pad 0.625 > 0.5
            time.sleep(0.05)
            stub.release.set()
            r1 = t1.result(timeout=60)
            r2 = t2.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r2.ok
        rec = sched.serve_stats()["recycle"]
        assert rec["cross_bucket_admissions"] == 0
        assert r2.bucket_len == 16 and r2.recycles == 6

    def test_cross_bucket_false_scrubbed_stats_identity(
            self, model_and_params):
        """The off switch: RecyclePolicy(cross_bucket=False) leaves
        scrubbed serve_stats() byte-identical to a policy that never
        mentioned the field (the same scrub discipline as the
        continuous=False pin in test_continuous.py)."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        def run_one(policy):
            _, sched = _scheduler(model_and_params, policy,
                                  num_recycles=1, buckets=(16,))
            reqs = requests_of((12, 8), key=9)
            with sched:
                for r in reqs:
                    assert sched.submit(
                        FoldRequest(seq=r.seq, msa=r.msa)).result(
                            timeout=300).ok
            return scrub(sched.serve_stats())

        explicit_off = run_one(RecyclePolicy(converge_tol=0.0,
                                             continuous=True,
                                             cross_bucket=False))
        never_heard = run_one(RecyclePolicy(converge_tol=0.0,
                                            continuous=True))
        assert json.dumps(explicit_off, sort_keys=True, default=str) \
            == json.dumps(never_heard, sort_keys=True, default=str)
        assert explicit_off["recycle"]["cross_bucket_admissions"] == 0
        assert explicit_off["recycle"]["cross_bucket"] is False

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            RecyclePolicy(cross_bucket=True)        # needs continuous
        with pytest.raises(ValueError):
            RecyclePolicy(eager_form=True)          # needs continuous
        with pytest.raises(ValueError):
            RecyclePolicy(continuous=True, cross_bucket=True,
                          cross_bucket_max_pad_frac=1.5)


class TestEagerForm:
    def test_thin_queue_forms_before_max_wait(self):
        """Admission-aware formation: with eager_form a single pending
        request launches its (under-filled) batch immediately instead
        of waiting out a long max_wait — max_wait is a fallback, not a
        latency floor."""
        stub = _ContStub({1: 1})
        sched = Scheduler(
            stub, BucketPolicy((32,)),
            SchedulerConfig(max_batch_size=4, max_wait_ms=10_000.0,
                            num_recycles=2, msa_depth=0),
            recycle_policy=RecyclePolicy(converge_tol=0.0,
                                         continuous=True,
                                         eager_form=True,
                                         preempt=False),
            metrics=ServeMetrics(registry=MetricsRegistry()),
            registry=MetricsRegistry())
        sched.start()
        try:
            t0 = time.monotonic()
            r = sched.submit(_req(1)).result(timeout=60)
            elapsed = time.monotonic() - t0
        finally:
            sched.stop()
        assert r.ok
        # served far below the 10s max_wait window
        assert elapsed < 5.0, elapsed

    def test_admission_tops_up_eager_batch(self):
        """The thin-queue batch that formed eagerly is topped up by
        mid-loop admission: a request arriving while the loop runs
        rides a free row instead of waiting for the next formation."""
        stub = _ContStub({1: 10 ** 9, 2: 10 ** 9})
        stub.gate_at = 1
        sched = Scheduler(
            stub, BucketPolicy((32,)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=10_000.0,
                            num_recycles=4, msa_depth=0),
            recycle_policy=RecyclePolicy(converge_tol=0.5,
                                         continuous=True,
                                         eager_form=True,
                                         preempt=False),
            metrics=ServeMetrics(registry=MetricsRegistry()),
            registry=MetricsRegistry())
        sched.start()
        try:
            t1 = sched.submit(_req(1))
            assert stub.reached.wait(timeout=60)
            t2 = sched.submit(_req(2))
            time.sleep(0.05)
            stub.release.set()
            r1 = t1.result(timeout=60)
            r2 = t2.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r2.ok
        rec = sched.serve_stats()["recycle"]
        assert rec["row_admissions"] == 1
        assert ("init_rows", [2]) in stub.calls


class TestContactPlanLengths:
    def test_padding_plans_as_dead_blocks(self):
        """Per-element lengths zero contact contributions beyond each
        row's real residues before the batch reduce — a shorter
        admitted row's padding region (and a dead row's garbage) can
        never mark a block live (ISSUE 13)."""
        from alphafold2_tpu.ops.block_sparse import \
            contact_probs_from_distogram

        n, nb = 16, 37
        logits = np.zeros((2, n, n, nb), np.float32)
        # both elements firmly non-contact everywhere...
        logits[:, :, :, -1] = 50.0
        # ...except element 1 screams "contact" in the far corner —
        # entirely inside the region beyond its real length
        logits[1, 12:, 12:, :] = 0.0
        logits[1, 12:, 12:, 0] = 50.0
        full = contact_probs_from_distogram(logits)
        masked = contact_probs_from_distogram(logits,
                                              lengths=[16, 8])
        assert full[12:, 12:].max() > 0.9
        assert masked[12:, 12:].max() < 0.1
        # a dead row (length 0) contributes nothing at all
        dead = contact_probs_from_distogram(logits, lengths=[0, 0])
        assert dead.max() == 0.0
        with pytest.raises(ValueError):
            contact_probs_from_distogram(logits, lengths=[16])


class TestLoadtestFlags:
    def test_cross_bucket_flags_fast(self, tmp_path, capsys):
        """Tier-1 flag-rot tripwire: the --cross-bucket /
        --cross-bucket-max-pad-frac / --eager-form surface drives a
        real (tiny) run and reports the cross-bucket fields."""
        import sys
        sys.path.insert(0, "tools")
        try:
            import serve_loadtest
        finally:
            sys.path.pop(0)
        rc = serve_loadtest.main([
            "--requests", "6", "--concurrency", "3",
            "--lengths", "7,12", "--buckets", "8,16",
            "--msa-depth", str(MSA_DEPTH), "--max-batch", "2",
            "--max-wait-ms", "5", "--num-recycles", "1",
            "--cross-bucket", "--cross-bucket-max-pad-frac", "0.9",
            "--eager-form",
            "--dim", "32", "--depth", "1",
            "--metrics-path", str(tmp_path / "m.jsonl")])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip()
                            .splitlines()[-1])
        assert report["continuous"] is True        # implied
        assert report["cross_bucket"] is True
        assert report["served"] == 6
        assert "cross_bucket_admissions" in report
        assert "cross_bucket_refusals" in report
        assert "padding_waste_admitted" in report
        assert "admit_pad_fraction" in report
        assert report["recycle"]["cross_bucket"] is True
        assert report["recycle"]["eager_form"] is True
        assert report["recycle"]["cross_bucket_max_pad_frac"] == 0.9
