"""Native data-loader tests: C++ parser vs pure-Python fallback parity on
synthetic a3m and PDB content, plus malformed-input handling."""

import numpy as np
import pytest

from alphafold2_tpu.data import featurize, native

A3M = """>query
ARNDCQEGHILK
>hit1 some description
ARNDCaaQEGHILK
>hit2
-RND.CQEGHIL-
"""

PDB = """HEADER    TEST
ATOM      1  N   ALA A   1      11.104   6.134  -6.504  1.00  0.00           N
ATOM      2  CA  ALA A   1      11.639   6.071  -5.147  1.00  0.00           C
ATOM      3  C   ALA A   1      10.560   5.704  -4.147  1.00  0.00           C
ATOM      4  O   ALA A   1       9.459   5.292  -4.533  1.00  0.00           O
ATOM      5  CB  ALA A   1      12.795   5.063  -5.068  1.00  0.00           C
ATOM      6  N   GLY A   2      10.871   5.844  -2.861  1.00  0.00           N
ATOM      7  CA  GLY A   2       9.912   5.520  -1.818  1.00  0.00           C
ATOM      8  C   GLY A   2      10.556   5.620  -0.441  1.00  0.00           C
ATOM      9  O   GLY A   2      11.775   5.730  -0.327  1.00  0.00           O
ATOM     10  N   TRP B   1       0.000   0.000   0.000  1.00  0.00           N
END
"""


@pytest.fixture(scope="module")
def has_native():
    return native.native_available()


class TestA3M:
    def test_native_builds(self, has_native):
        # g++ is baked into the image; the native build must succeed here
        assert has_native, "libaf2data.so failed to build/load"

    def test_parse_shapes_and_tokens(self):
        toks = native.parse_a3m(A3M)
        assert toks.shape == (3, 12)
        expect = featurize.tokenize("ARNDCQEGHILK")
        assert np.array_equal(toks[0].astype(np.int32), expect)
        # insertions removed from hit1 -> identical to query
        assert np.array_equal(toks[1], toks[0])
        # gaps -> padding token
        assert toks[2, 0] == featurize.AA_INDEX["_"]
        assert toks[2, -1] == featurize.AA_INDEX["_"]

    def test_native_matches_python(self, has_native):
        if not has_native:
            pytest.skip("no native lib")
        a = native.parse_a3m(A3M)
        b = native._parse_a3m_py(A3M)
        assert np.array_equal(a, b)

    def test_ragged_rejected(self):
        bad = ">a\nARND\n>b\nARNDC\n"
        with pytest.raises(ValueError):
            native.parse_a3m(bad)
        with pytest.raises(ValueError):
            native._parse_a3m_py(bad)

    def test_raw_sequences_without_headers(self):
        toks = native.parse_a3m("ARND\n")
        assert toks.shape == (1, 4)


class TestPDB:
    def test_parse_first_chain(self):
        seq, coords, mask = native.parse_pdb(PDB)
        assert seq.shape == (2,)
        assert seq[0] == featurize.AA_INDEX["A"]
        assert seq[1] == featurize.AA_INDEX["G"]
        assert coords.shape == (2, 14, 3)
        # ALA: N CA C O CB present
        assert mask[0, :5].all() and not mask[0, 5:].any()
        # GLY: backbone only
        assert mask[1, :4].all() and not mask[1, 4:].any()
        assert np.isclose(coords[0, 1, 0], 11.639)

    def test_chain_selection(self):
        seq, coords, mask = native.parse_pdb(PDB, chain="B")
        assert seq.shape == (1,)
        assert seq[0] == featurize.AA_INDEX["W"]

    def test_native_matches_python(self, has_native):
        if not has_native:
            pytest.skip("no native lib")
        a = native.parse_pdb(PDB)
        b = native._parse_pdb_py(PDB)
        for x, y in zip(a, b):
            assert np.allclose(np.asarray(x, np.float64),
                               np.asarray(y, np.float64))

    def test_interleaved_residues_native_python_agree(self, has_native):
        # residue identity is sequential (resseq, icode) change-detection
        # in BOTH backends: residue 1 reappearing after residue 2 starts a
        # third residue instead of merging into the first
        interleaved = "\n".join([
            "ATOM      1  N   ALA A   1      1.000   0.000   0.000"
            "  1.00  0.00           N",
            "ATOM      2  CA  ALA A   1      2.000   0.000   0.000"
            "  1.00  0.00           C",
            "ATOM      3  N   GLY A   2      3.000   0.000   0.000"
            "  1.00  0.00           N",
            "ATOM      4  CA  ALA A   1      4.000   0.000   0.000"
            "  1.00  0.00           C",
            "END",
        ]) + "\n"
        b = native._parse_pdb_py(interleaved)
        assert b[0].shape == (3,)          # ALA, GLY, ALA — not merged
        assert np.isclose(b[1][0, 1, 0], 2.0)   # first ALA CA untouched
        assert np.isclose(b[1][2, 1, 0], 4.0)   # revisited ALA is residue 3
        if has_native:
            a = native.parse_pdb(interleaved)
            for x, y in zip(a, b):
                assert np.allclose(np.asarray(x, np.float64),
                                   np.asarray(y, np.float64))

    def test_roundtrip_with_featurize(self):
        seq, coords, mask = native.parse_pdb(PDB)
        # feeds straight into the distance-target path
        d = featurize.distance_map_targets(coords, seq,
                                           mask[:, :4].all(-1))
        assert d.shape == (2, 2)
