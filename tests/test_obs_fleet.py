"""Fleet-wide observability tests (ISSUE 15): cross-process trace
propagation (TraceContext on the wire, origin-tagged tracers, continued
traces) across all four hop types over real HTTP — forward, raw
feature-key forward, peer-cache fetch, transport-death failover — plus
the SLO engine unit suite (budget math, burn-rate windows, class
mapping), the `/metrics` exposition endpoints, the STAGE_ORDER drift
tripwire, and the tools/obs_fleet.py stitch checker.

The HTTP tier is stub-executor + localhost servers (the
test_frontdoor.py convention) — no model, no processes; serve_smoke.sh
phase 14 runs the full 3-process version of the same story.
"""

import dataclasses
import importlib.util
import json
import os
import threading
import time
import types

import numpy as np
import pytest

from alphafold2_tpu import fleet
from alphafold2_tpu.cache import FoldCache, fold_key
from alphafold2_tpu.cache.keys import feature_key
from alphafold2_tpu.fleet.frontdoor import FrontDoorServer
from alphafold2_tpu.fleet.peer import PeerCacheClient, PeerCacheServer
from alphafold2_tpu.fleet.rpc import HttpTransport
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.obs.slo import (SLOClass, SLOEngine, SLOPolicy,
                                    burn_rate, evaluate_class,
                                    quantize_target)
from alphafold2_tpu.obs.trace import NULL_TRACE, TraceContext, Tracer
from alphafold2_tpu.obs.export import prometheus_text
from alphafold2_tpu.serve import (BucketPolicy, FeaturePool, FoldRequest,
                                  RawFoldRequest, Scheduler,
                                  SchedulerConfig)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MSA_DEPTH = 3


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


obs_report = _load_tool("obs_report")
obs_fleet = _load_tool("obs_fleet")


class _OkExecutor:
    """Deterministic stub; optional gate Event blocks every run until
    set (the mid-fold owner-death window)."""

    def __init__(self, gate=None):
        self.gate = gate
        self.calls = 0

    def run(self, batch, num_recycles, trace=None):
        if self.gate is not None:
            self.gate.wait(timeout=30)
        self.calls += 1
        b, n = batch["seq"].shape
        if trace is not None:
            # the real FoldExecutor records the fold span; the stub
            # must too or check_traces' accelerator rule fires
            with trace.span("fold"):
                time.sleep(0.001)

        class R:
            coords = np.zeros((b, n, 3), np.float32)
            confidence = np.full((b, n), 0.5, np.float32)

        return R()

    def stats(self):
        return {"calls": self.calls}


def _request(seed=0, n=12, **kwargs):
    rng = np.random.default_rng(seed)
    return FoldRequest(
        seq=rng.integers(0, 20, size=n).astype(np.int32),
        msa=rng.integers(0, 20, size=(MSA_DEPTH, n)).astype(np.int32),
        **kwargs)


def _scheduler(tracer, executor=None, **kwargs):
    return Scheduler(
        executor or _OkExecutor(), BucketPolicy((16,)),
        SchedulerConfig(max_batch_size=2, max_wait_ms=10.0, poll_ms=2.0,
                        msa_depth=MSA_DEPTH),
        model_tag="v1", registry=MetricsRegistry(), tracer=tracer,
        **kwargs)


# -- TraceContext wire format --------------------------------------------


@pytest.mark.quick
class TestTraceContext:
    def test_header_roundtrip(self):
        ctx = TraceContext("t1.r0.abc", "s3", origin="r0")
        back = TraceContext.from_headers(ctx.to_headers())
        assert back == ctx

    def test_originless_context_omits_origin_header(self):
        ctx = TraceContext("t1", "s0")
        h = ctx.to_headers()
        assert "X-Trace-Origin" not in h
        assert TraceContext.from_headers(h) == ctx

    def test_absent_headers_decode_none(self):
        assert TraceContext.from_headers({}) is None
        assert TraceContext.from_headers({"X-Other": "1"}) is None

    def test_null_trace_has_no_wire_context(self):
        assert NULL_TRACE.wire_context() is None


class TestTracerOrigin:
    def test_origin_makes_ids_unique_across_boots(self):
        a = Tracer(origin="r0")
        b = Tracer(origin="r0")   # same replica id, new boot
        ta, tb = a.start_trace("x"), b.start_trace("x")
        assert ta.trace_id != tb.trace_id
        assert "r0" in ta.trace_id

    def test_originless_tracer_keeps_compact_ids(self):
        t = Tracer().start_trace("x")
        assert t.trace_id.startswith("t") and "." not in t.trace_id

    def test_record_carries_origin_and_parent_fields(self, tmp_path):
        sender = Tracer(origin="r0")
        receiver = Tracer(origin="r1")
        t0 = sender.start_trace("req")
        ctx = t0.wire_context()
        assert ctx.trace_id == t0.trace_id and ctx.origin == "r0"
        t1 = receiver.start_trace("req", context=ctx)
        assert t1.trace_id == t0.trace_id
        t1.finish("ok")
        rec = receiver.slowest()[0]
        assert rec["origin"] == "r1"
        assert rec["parent_span_id"] == ctx.parent_span_id
        assert rec["parent_origin"] == "r0"
        t0.finish("ok")
        rec0 = sender.slowest()[0]
        assert rec0["origin"] == "r0"
        assert "parent_span_id" not in rec0

    def test_wire_context_mints_fresh_span_ids(self):
        t = Tracer(origin="r0").start_trace("x")
        a, b = t.wire_context(), t.wire_context()
        assert a.parent_span_id != b.parent_span_id
        t.finish("ok")
        assert t.wire_context() is None


# -- SLO policy / engine -------------------------------------------------


@pytest.mark.quick
class TestSLOPolicy:
    def test_parse_buckets_and_all(self):
        pol = SLOPolicy.parse("32=400,all=2000", window_s=60)
        assert pol.window_s == 60
        by_name = {c.name: c for c in pol.classes}
        assert by_name["bucket32"].buckets == (32,)
        assert by_name["bucket32"].target_s == pytest.approx(0.4)
        assert by_name["all"].buckets == ()
        assert by_name["all"].covers(32) and by_name["all"].covers(64)
        assert not by_name["bucket32"].covers(64)

    def test_parse_auto_target(self):
        pol = SLOPolicy.parse("32=auto")
        assert pol.classes[0].target_s is None
        # availability objective still stands — the engine accepts it
        SLOEngine(pol, registry=MetricsRegistry())

    @pytest.mark.parametrize("bad", ["32", "foo=100", "32=slow",
                                     "", ","])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(ValueError):
            SLOPolicy.parse(bad)

    def test_class_validation(self):
        with pytest.raises(ValueError):
            SLOClass(name="", target_s=1.0)
        with pytest.raises(ValueError):
            SLOClass(name="x", target_s=-1.0)
        with pytest.raises(ValueError):
            SLOClass(name="x", target_s=1.0, percentile=0.0)
        with pytest.raises(ValueError):
            SLOPolicy(classes=[SLOClass("a", 1.0), SLOClass("a", 2.0)])

    def test_quantize_target_picks_nearest_edge(self):
        edges = (0.128, 0.256, 0.512, 1.024)
        assert quantize_target(0.5, edges) == 0.512
        assert quantize_target(0.3, edges) == 0.256

    def test_burn_rate_math(self):
        assert burn_rate(0.0, 0.01) == 0.0
        assert burn_rate(0.01, 0.01) == pytest.approx(1.0)
        assert burn_rate(0.05, 0.01) == pytest.approx(5.0)
        assert burn_rate(0.5, 0.0) >= 1e9   # zero-allowance objective


class TestSLOEngine:
    def _rig(self, spec="32=500", window_s=10.0):
        reg = MetricsRegistry()
        hist = reg.histogram("serve_request_latency_seconds", "",
                             ("bucket_len",))
        out = reg.counter("serve_requests_total", "", ("outcome",))
        clock = [0.0]
        engine = SLOEngine(SLOPolicy.parse(spec, window_s=window_s),
                           registry=reg, clock=lambda: clock[0])
        return reg, hist, out, clock, engine

    def test_budget_math_exact_burn(self):
        reg, hist, out, clock, engine = self._rig()
        for _ in range(99):
            hist.observe(0.01, bucket_len=32)
        hist.observe(10.0, bucket_len=32)     # 1/100 over target
        out.inc(100, outcome="served")
        clock[0] = 1.0
        rep = engine.report()
        lat = rep["classes"]["bucket32"]["latency"]
        assert rep["classes"]["bucket32"]["requests"] == 100
        assert lat["attainment"] == pytest.approx(0.99)
        assert lat["burn_rate"] == pytest.approx(1.0)
        assert lat["budget_remaining"] == pytest.approx(0.0)
        assert lat["met"]   # p99 at exactly 99% within target

    def test_burn_rate_window_rolls_off(self):
        reg, hist, out, clock, engine = self._rig(window_s=10.0)
        hist.observe(10.0, bucket_len=32)      # every request slow
        out.inc(1, outcome="served")
        clock[0] = 1.0
        rep = engine.report()
        assert rep["classes"]["bucket32"]["latency"]["burn_rate"] > 1.0
        # 20s later with no new traffic the bad window has rolled off
        clock[0] = 20.0
        engine.report()
        clock[0] = 21.0
        rep2 = engine.report()
        assert rep2["classes"]["bucket32"]["requests"] == 0
        assert rep2["classes"]["bucket32"]["latency"]["burn_rate"] == 0.0

    def test_class_bucket_mapping(self):
        reg, hist, out, clock, engine = self._rig(
            spec="32=500,all=500")
        # bucket 64 traffic is slow; bucket 32 traffic is fast
        for _ in range(10):
            hist.observe(0.01, bucket_len=32)
            hist.observe(10.0, bucket_len=64)
        clock[0] = 1.0
        rep = engine.report()
        b32 = rep["classes"]["bucket32"]["latency"]
        allc = rep["classes"]["all"]["latency"]
        assert b32["attainment"] == pytest.approx(1.0)
        assert allc["attainment"] == pytest.approx(0.5)

    def test_availability_counts_bad_statuses(self):
        reg, hist, out, clock, engine = self._rig()
        out.inc(98, outcome="served")
        out.inc(1, outcome="error")
        out.inc(1, outcome="shed")   # not in DEFAULT_BAD_STATUSES
        clock[0] = 1.0
        rep = engine.report()
        avail = rep["classes"]["bucket32"]["availability"]
        assert avail["bad"] == 1
        assert avail["observed"] == pytest.approx(0.99)
        assert avail["burn_rate"] == pytest.approx(1.0)

    def test_gauges_land_in_exposition(self):
        reg, hist, out, clock, engine = self._rig()
        hist.observe(0.01, bucket_len=32)
        out.inc(1, outcome="served")
        clock[0] = 1.0
        engine.report()
        text = prometheus_text(reg)
        for name in ("slo_latency_attainment", "slo_latency_burn_rate",
                     "slo_error_budget_remaining", "slo_availability"):
            assert f'{name}{{objective="bucket32"}}' in text
        assert obs_report.check_prometheus_text(text) == []

    def test_availability_only_class(self):
        reg = MetricsRegistry()
        out = reg.counter("serve_requests_total", "", ("outcome",))
        engine = SLOEngine(
            SLOPolicy(classes=[SLOClass("av", target_s=None,
                                        availability=0.9)],
                      window_s=10.0),
            registry=reg, clock=lambda: 1.0)
        out.inc(1, outcome="error")
        rep = engine.report(now=2.0)
        assert "latency" not in rep["classes"]["av"]
        assert not rep["classes"]["av"]["availability"]["met"]


class TestSchedulerSLO:
    def test_serve_stats_slo_block(self):
        reg = MetricsRegistry()
        from alphafold2_tpu.serve.metrics import ServeMetrics
        engine = SLOEngine(SLOPolicy.parse("16=60000", window_s=60),
                           registry=reg)
        sched = Scheduler(
            _OkExecutor(), BucketPolicy((16,)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                            poll_ms=2.0, msa_depth=MSA_DEPTH),
            metrics=ServeMetrics(registry=reg), registry=reg,
            slo=engine)
        with sched:
            assert sched.submit(_request()).result(timeout=30).ok
        stats = sched.serve_stats()
        cls = stats["slo"]["classes"]["bucket16"]
        assert cls["requests"] >= 1
        assert cls["latency"]["met"] and cls["ok"]

    def test_off_by_default_no_slo_keys_or_metrics(self):
        reg = MetricsRegistry()
        from alphafold2_tpu.serve.metrics import ServeMetrics
        sched = Scheduler(
            _OkExecutor(), BucketPolicy((16,)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                            poll_ms=2.0, msa_depth=MSA_DEPTH),
            metrics=ServeMetrics(registry=reg), registry=reg)
        with sched:
            assert sched.submit(_request()).result(timeout=30).ok
        stats = sched.serve_stats()
        assert "slo" not in stats
        assert not [m.name for m in reg.metrics()
                    if m.name.startswith("slo_")]


# -- /metrics endpoints --------------------------------------------------


class TestMetricsEndpoints:
    def test_frontdoor_metrics_parses(self):
        import urllib.request

        reg = MetricsRegistry()
        reg.counter("demo_total", "demo").inc(3)
        sched = _scheduler(Tracer())
        server = FrontDoorServer(sched, replica_id="r0", metrics=reg)
        hook_calls = []
        server.metrics_hook = lambda: hook_calls.append(1)
        with sched, server:
            with urllib.request.urlopen(server.url + "/metrics",
                                        timeout=5) as resp:
                assert resp.status == 200
                assert "text/plain" in resp.headers["Content-Type"]
                text = resp.read().decode("utf-8")
        assert "demo_total 3" in text
        assert "fleet_rpc_served_total" in text
        assert hook_calls == [1]
        assert obs_report.check_prometheus_text(text) == []

    def test_peer_server_metrics_parses(self):
        import urllib.request

        reg = MetricsRegistry()
        reg.counter("demo_total", "demo").inc(1)
        cache = FoldCache(registry=MetricsRegistry())
        partition = threading.Event()
        server = PeerCacheServer(cache, replica_id="r1", metrics=reg,
                                 partition=partition)
        with server:
            host, port = server.address
            with urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=5) as resp:
                assert resp.status == 200
                text = resp.read().decode("utf-8")
            # the scrape survives an induced partition (control plane,
            # same rule as the front door): the chaos window is when
            # the numbers matter
            partition.set()
            with urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=5) as resp:
                assert resp.status == 200
        assert "fleet_peer_served_total" in text
        assert obs_report.check_prometheus_text(text) == []

    def test_pipeline_scheduler_passes_trace_through(self):
        from alphafold2_tpu.serve import PipelineScheduler

        pool = FeaturePool(workers=1, registry=MetricsRegistry())
        tracer = Tracer(origin="r0")
        sched = _scheduler(tracer, feature_pool=pool)
        pipe = PipelineScheduler(sched, pool)
        assert pipe.tracer is tracer
        with pipe:
            ctx = Tracer(origin="driver").start_trace(
                "x").wire_context()
            trace = pipe.tracer.start_trace("x", context=ctx)
            resp = pipe.submit(_request(), trace=trace).result(
                timeout=30)
        assert resp.ok
        rec = tracer.slowest()[0]
        assert rec["trace_id"] == ctx.trace_id
        assert rec["parent_span_id"] == ctx.parent_span_id


# -- STAGE_ORDER drift tripwire ------------------------------------------


@pytest.mark.quick
class TestStageOrderTripwire:
    def _rec(self, span_name):
        return {"schema": 1, "trace_id": "t0", "request_id": "r",
                "status": "ok", "source": "cache", "duration_s": 1.0,
                "spans": [{"name": span_name, "start_s": 0.0,
                           "dur_s": 0.5}], "events": []}

    def test_unknown_span_name_is_flagged(self):
        problems = obs_report.check_stage_order(
            [self._rec("totally_new_stage")])
        assert len(problems) == 1
        assert "totally_new_stage" in problems[0]
        assert "STAGE_ORDER" in problems[0]

    def test_known_names_pass(self):
        recs = [self._rec(name) for name in obs_report.STAGE_ORDER]
        assert obs_report.check_stage_order(recs) == []

    def test_peer_serve_is_canonical(self):
        assert "peer_serve" in obs_report.STAGE_ORDER


# -- obs_fleet stitch checker (synthetic records) ------------------------


def _parent_rec(outcome="ok", span_id="s0", auto_closed=False,
                origin="r0"):
    attrs = {"peer": "http://x", "route": "submit", "outcome": outcome,
             "span_id": span_id}
    if auto_closed:
        attrs = {"auto_closed": True}
    return {"schema": 1, "trace_id": "T1", "request_id": "req",
            "status": "ok", "source": "forwarded", "origin": origin,
            "duration_s": 1.0, "start_unix_s": 1.0,
            "spans": [{"name": "rpc", "start_s": 0.1, "dur_s": 0.8,
                       "attrs": attrs}],
            "events": []}


def _child_rec(parent="s0", origin="r1"):
    return {"schema": 1, "trace_id": "T1", "request_id": "req",
            "status": "ok", "source": "fold", "origin": origin,
            "duration_s": 0.5, "start_unix_s": 1.2,
            "parent_span_id": parent, "parent_origin": "r0",
            "spans": [{"name": "fold", "start_s": 0.0, "dur_s": 0.4}],
            "events": []}


class TestObsFleetChecker:
    def test_complete_stitch_is_clean(self):
        st = obs_fleet.stitch([_parent_rec(), _child_rec()])
        assert obs_fleet.check_stitches(st) == []
        stitched = [s for s in st.values() if s.hops > 1]
        assert len(stitched) == 1
        assert stitched[0].origins == ["r0", "r1"]

    def test_broken_stitch_flagged(self):
        st = obs_fleet.stitch([_parent_rec()])   # armed hop, no child
        problems = obs_fleet.check_stitches(st)
        assert len(problems) == 1 and "BROKEN STITCH" in problems[0]

    def test_transport_death_hop_requires_no_child(self):
        st = obs_fleet.stitch([_parent_rec(outcome="transport_death")])
        assert obs_fleet.check_stitches(st) == []

    def test_auto_closed_rpc_span_flagged(self):
        st = obs_fleet.stitch([_parent_rec(auto_closed=True)])
        problems = obs_fleet.check_stitches(st)
        assert len(problems) == 1 and "left open" in problems[0]

    def test_unanchored_child_warns_but_does_not_fail(self):
        # a kill -9 tears exactly this way: the dead sender's record
        # never flushed but the owner's continued record did — the
        # chaos the fleet survives must not fail its own tripwire
        st = obs_fleet.stitch([_child_rec(parent="s99")])
        assert obs_fleet.check_stitches(st) == []
        warnings = obs_fleet.unanchored_warnings(st)
        assert len(warnings) == 1 and "torn" in warnings[0]
        assert obs_fleet.summarize(st, [_child_rec(parent="s99")])[
            "unanchored_records"] == 1

    def test_span_ids_disambiguate_by_origin(self):
        # a 3-hop chain where BOTH hops mint "s0": each process's
        # continued trace has its own span-id sequence, so the child
        # must attach via (parent_origin, span_id), never span_id
        # alone
        driver = _parent_rec(span_id="s0", origin="driver")
        mid = _child_rec(parent="s0", origin="r0")
        mid["parent_origin"] = "driver"
        mid["source"] = "forwarded"
        mid["spans"].append(
            {"name": "rpc", "start_s": 0.05, "dur_s": 0.3,
             "attrs": {"peer": "http://r1", "route": "submit",
                       "outcome": "ok", "span_id": "s0"}})
        leaf = _child_rec(parent="s0", origin="r1")
        leaf["parent_origin"] = "r0"
        st = obs_fleet.stitch([driver, mid, leaf])
        assert obs_fleet.check_stitches(st) == []
        tr = list(st.values())[0]
        assert tr.children_of[("driver", "s0")] == [mid]
        assert tr.children_of[("r0", "s0")] == [leaf]
        text = "\n".join(obs_fleet.render_stitched(tr))
        # the leaf renders exactly once, nested under r0
        assert text.count("[r1]") == 1

    def test_wrong_origin_parent_is_a_broken_stitch(self):
        parent = _parent_rec(span_id="s0", origin="r0")
        child = _child_rec(parent="s0", origin="r1")
        child["parent_origin"] = "r9"    # continues SOMEONE ELSE's s0
        st = obs_fleet.stitch([parent, child])
        problems = obs_fleet.check_stitches(st)
        # r0's armed hop has no child (hard failure); the stray child
        # itself is only an unanchored warning
        assert len(problems) == 1 and "BROKEN STITCH" in problems[0]
        assert len(obs_fleet.unanchored_warnings(st)) == 1

    def test_merge_dedupes_identical_records(self, tmp_path):
        path = tmp_path / "a.jsonl"
        rec = _parent_rec()
        path.write_text(json.dumps(rec) + "\n")
        records, problems = obs_fleet.load_all_traces(
            [str(path), str(path)])
        assert len(records) == 1 and problems == []

    def test_render_stitched_anchors_child_at_parent_span(self):
        st = obs_fleet.stitch([_parent_rec(), _child_rec()])
        stitched = [s for s in st.values() if s.hops > 1][0]
        text = "\n".join(obs_fleet.render_stitched(stitched))
        assert "[r0]" in text and "[r1]" in text
        # child fold span renders at rpc start (0.1) + own offset (0.0)
        assert "0.1000s +0.4000s  fold" in text

    def test_prometheus_parse_and_slo_table(self):
        reg = MetricsRegistry()
        reg.gauge("slo_latency_burn_rate", "", ("objective",)).set(
            2.5, objective="bucket32")
        text = prometheus_text(reg)
        parsed = obs_fleet.parse_prometheus(text)
        assert parsed["slo_latency_burn_rate"][0] == (
            {"objective": "bucket32"}, 2.5)
        table = obs_fleet.slo_gauge_table({"r0.prom": text})
        assert table["bucket32"]["r0.prom"]["latency_burn_rate"] == 2.5


# -- the four hop types over real HTTP -----------------------------------


class _Rig:
    """Two replicas: r1 behind a FrontDoorServer (+ optional peer
    cache server), r0 routing to it via HttpTransport — each with an
    origin-tagged tracer writing JSONL into tmp_path."""

    def __init__(self, tmp_path, executor1=None, r0_kwargs=None,
                 transport_kwargs=None):
        self.tmp = str(tmp_path)
        self.tracer0 = Tracer(
            jsonl_path=os.path.join(self.tmp, "r0.jsonl"), origin="r0")
        self.tracer1 = Tracer(
            jsonl_path=os.path.join(self.tmp, "r1.jsonl"), origin="r1")
        self.s1 = _scheduler(self.tracer1, executor=executor1)
        self.fd1 = FrontDoorServer(self.s1, replica_id="r1",
                                   metrics=MetricsRegistry())
        self.s1.start()
        self.fd1.start()
        self.registry = fleet.ReplicaRegistry(
            model_tag="v1", registry=MetricsRegistry())
        self.registry.register("r0")
        self.transport = HttpTransport(self.fd1.url,
                                       metrics=MetricsRegistry(),
                                       **(transport_kwargs or {}))
        self.registry.register("r1", transport=self.transport)
        self.router = fleet.ConsistentHashRouter(
            self.registry, "r0", metrics=MetricsRegistry())
        self.cache0 = FoldCache(registry=MetricsRegistry())
        self.s0 = _scheduler(self.tracer0, router=self.router,
                             cache=self.cache0, **(r0_kwargs or {}))
        self.s0.start()

    def owned_by_r1(self):
        for s in range(300):
            req = _request(seed=s)
            key = fold_key(req.seq, req.msa, msa_depth=MSA_DEPTH,
                           num_recycles=self.s0.config.num_recycles,
                           model_tag="v1")
            if self.router.owner_for(key) == "r1":
                return req
        raise AssertionError("no key owned by r1")

    def close(self):
        for closer in (self.s0.stop, self.s1.stop, self.fd1.stop,
                       self.tracer0.close, self.tracer1.close):
            try:
                closer()
            except Exception:
                pass

    def merged(self):
        records, problems = obs_fleet.load_all_traces(
            [os.path.join(self.tmp, "r0.jsonl"),
             os.path.join(self.tmp, "r1.jsonl")])
        assert problems == []
        return records


def _assert_one_stitched(records, hops=2):
    stitched = obs_fleet.stitch(records)
    assert obs_fleet.check_stitches(stitched) == []
    assert obs_report.check_traces(records) == []
    assert obs_report.check_stage_order(records) == []
    multi = [st for st in stitched.values() if st.hops > 1]
    assert len(multi) == 1
    assert multi[0].hops == hops
    return multi[0]


class TestHttpStitching:
    def test_forward_hop_stitches(self, tmp_path):
        rig = _Rig(tmp_path)
        try:
            req = rig.owned_by_r1()
            resp = rig.s0.submit(req).result(timeout=30)
            assert resp.ok and resp.source == "forwarded"
        finally:
            rig.close()
        st = _assert_one_stitched(rig.merged())
        assert st.origins == ["r0", "r1"]
        root = st.roots[0]
        assert root["origin"] == "r0"
        rpc = [s for s in root["spans"] if s["name"] == "rpc"]
        assert rpc and rpc[0]["attrs"]["outcome"] == "ok"
        child = st.children_of[("r0", rpc[0]["attrs"]["span_id"])][0]
        assert child["origin"] == "r1"
        assert any(s["name"] == "fold" for s in child["spans"])

    def test_forward_raw_hop_stitches(self, tmp_path):
        pool = FeaturePool(workers=1, registry=MetricsRegistry())
        rig = _Rig(tmp_path, r0_kwargs={"feature_pool": pool})
        try:
            raw = None
            for s in range(300):
                rng = np.random.default_rng(s)
                cand = RawFoldRequest(
                    seq=rng.integers(0, 20, size=12).astype(np.int32),
                    msa=rng.integers(0, 20,
                                     size=(MSA_DEPTH, 12)).astype(
                                         np.int32))
                key = feature_key(cand.seq, cand.msa,
                                  config_digest=pool.config_digest)
                if rig.router.owner_for(key) == "r1":
                    raw = cand
                    break
            assert raw is not None
            resp = rig.s0.submit_raw(raw).result(timeout=30)
            assert resp.ok and resp.source == "forwarded"
            pool.stop()
        finally:
            rig.close()
        st = _assert_one_stitched(rig.merged())
        root = st.roots[0]
        rpc = [s for s in root["spans"] if s["name"] == "rpc"]
        assert rpc and rpc[0]["attrs"]["route"] == "submit_raw"
        child = st.children_of[("r0", rpc[0]["attrs"]["span_id"])][0]
        assert child["origin"] == "r1"

    def test_peer_fetch_hop_stitches(self, tmp_path):
        tracer0 = Tracer(jsonl_path=str(tmp_path / "r0.jsonl"),
                         origin="r0")
        tracer1 = Tracer(jsonl_path=str(tmp_path / "r1.jsonl"),
                         origin="r1")
        cache1 = FoldCache(registry=MetricsRegistry())
        server = PeerCacheServer(cache1, replica_id="r1",
                                 metrics=MetricsRegistry())
        server.tracer = tracer1
        server.start()
        try:
            registry = fleet.ReplicaRegistry(model_tag="v1",
                                             registry=MetricsRegistry())
            registry.register("r0")
            registry.register("r1", peer_addr=server.address)
            router = fleet.ConsistentHashRouter(
                registry, "r0", metrics=MetricsRegistry())
            client = PeerCacheClient(registry, "r0", router=router,
                                     metrics=MetricsRegistry())
            cache0 = FoldCache(registry=MetricsRegistry(), peer=client)
            key = None
            for s in range(300):
                req = _request(seed=s)
                cand = fold_key(req.seq, req.msa, msa_depth=MSA_DEPTH,
                                num_recycles=0, model_tag="v1")
                if router.owner_for(cand) == "r1":
                    key = cand
                    break
            assert key is not None
            cache1.put(key, np.zeros((12, 3), np.float32),
                       np.full((12,), 0.5, np.float32))
            trace = tracer0.start_trace("peer-req")
            hit = cache0.get(key, trace=trace)
            assert hit is not None
            trace.finish("ok", source="cache")
        finally:
            server.stop()
            tracer0.close()
            tracer1.close()
        records, problems = obs_fleet.load_all_traces(
            [str(tmp_path / "r0.jsonl"), str(tmp_path / "r1.jsonl")])
        assert problems == []
        st = _assert_one_stitched(records)
        child = [r for r in st.records if r.get("origin") == "r1"][0]
        assert any(s["name"] == "peer_serve" for s in child["spans"])
        root = st.roots[0]
        ev = [e for e in root["events"] if e["name"] == "peer_fetch"][0]
        assert ev["attrs"]["outcome"] == "hit"
        assert child["parent_span_id"] == ev["attrs"]["span_id"]

    def test_failover_resubmit_closes_rpc_span(self, tmp_path):
        gate = threading.Event()
        rig = _Rig(tmp_path, executor1=_OkExecutor(gate=gate),
                   transport_kwargs={"poll_wait_s": 0.2,
                                     "timeout_s": 1.0})
        try:
            req = rig.owned_by_r1()
            ticket = rig.s0.submit(req)     # forwarded; r1 blocked
            time.sleep(0.2)
            rig.fd1.stop()                  # owner dies mid-fold
            resp = ticket.result(timeout=30)
            assert resp.ok and resp.source == "fold"   # failover fold
            assert rig.s0.serve_stats()["failovers"] == 1
            gate.set()                      # release r1's worker
            time.sleep(0.2)
        finally:
            gate.set()
            rig.close()
        records = rig.merged()
        root = [r for r in records if r.get("origin") == "r0"][0]
        rpc = [s for s in root["spans"] if s["name"] == "rpc"]
        assert rpc, "driver-side rpc span missing"
        attrs = rpc[0]["attrs"]
        assert attrs["outcome"] == "transport_death"
        assert "auto_closed" not in attrs
        # forward span explicitly closed too, then the local refold
        names = [s["name"] for s in root["spans"]]
        assert "forward" in names and "fold" in names
        assert any(e["name"] == "failover_local"
                   for e in root["events"])
        # the stitch checker is green: a dead-owner hop promises no
        # child, and nothing dangles open
        assert obs_fleet.check_stitches(obs_fleet.stitch(records)) == []


# -- driver-side SLO windows (loadtest helper) ---------------------------


class TestDriverSloReport:
    def test_kill_window_burns_after_calibration(self):
        loadtest = _load_tool("serve_loadtest")
        args = types.SimpleNamespace(slo="all=auto", slo_window_s=2.0)
        samples = []
        # healthy phase: 0-5s, fast
        for i in range(50):
            samples.append({"t": i * 0.1, "lat": 0.05, "bucket": 32,
                            "ok": True})
        # kill at t=5: affected requests pay the failover penalty
        for i in range(10):
            samples.append({"t": 5.2 + i * 0.2, "lat": 1.5,
                            "bucket": 32, "ok": True})
        rep = loadtest._driver_slo_report(args, samples,
                                          {"kill": 5.0}, 5.0)
        assert rep["samples"] == 60
        assert rep["classes"]["all"]["target_s"] < 1.0
        assert rep["kill_window_burn"] > 0
        # the healthy windows never burned
        pre_kill = [w for w in rep["windows"] if w["t1"] <= 5.0]
        assert pre_kill
        assert all(c["latency_burn"] == 0.0
                   for w in pre_kill for c in w["classes"].values())

    def test_flag_rot(self):
        loadtest = _load_tool("serve_loadtest")
        args = loadtest.parse_args(
            ["--slo", "32=400,all=auto", "--slo-window-s", "3",
             "--obs-fleet-out", "/tmp/x", "--procs", "3"])
        assert args.slo == "32=400,all=auto"
        assert args.slo_window_s == 3.0
        assert args.obs_fleet_out == "/tmp/x"
