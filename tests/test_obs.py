"""Observability subsystem tests (ISSUE 3): the metrics registry
(counter/gauge/histogram + Prometheus/JSONL export), request-scoped
tracing through the serving scheduler (cache hits, coalescing links,
shed reasons, follower deadlines), and the obs_report tooling.

Scheduler-level tests run against a stub executor (no model, no XLA) so
trace *propagation* is exercised fast; the real-executor compile/fold
span split and the 32-request e2e with obs enabled live in
tests/test_serve.py next to the serving acceptance demo.
"""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from alphafold2_tpu import obs
from alphafold2_tpu.obs.trace import NULL_TRACE
from alphafold2_tpu.serve import (BucketPolicy, FoldCache, FoldRequest,
                                  Scheduler, SchedulerConfig, ServeMetrics)
from alphafold2_tpu.utils.logging import MetricsLogger
from alphafold2_tpu.utils.profiling import StepTimer, percentile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(_REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


obs_report = _load_obs_report()


class _StubResult:
    def __init__(self, b, n):
        self.coords = np.zeros((b, n, 3), np.float32)
        self.confidence = np.ones((b, n), np.float32)


class _StubExecutor:
    """Executor-shaped stand-in: instant folds, optional delay/raise."""

    def __init__(self, delay_s=0.0, boom=False):
        self.delay_s = delay_s
        self.boom = boom

    def run(self, batch, num_recycles, trace=NULL_TRACE):
        if self.boom:
            raise RuntimeError("boom")
        with trace.span("fold"):
            if self.delay_s:
                time.sleep(self.delay_s)
            b, n = batch["seq"].shape
            return _StubResult(b, n)

    def stats(self):
        return {"hits": 0, "misses": 0, "evictions": 0, "resident": 0,
                "max_entries": 1, "keys": []}


def _requests(*lengths, seed=0, **kwargs):
    rng = np.random.default_rng(seed)
    return [FoldRequest(seq=rng.integers(0, 20, n), **kwargs)
            for n in lengths]


@pytest.mark.quick
class TestRegistry:
    def test_counter_gauge_labels_and_reuse(self):
        reg = obs.MetricsRegistry()
        c = reg.counter("reqs_total", "requests", ("outcome",))
        c.inc(outcome="ok")
        c.inc(2, outcome="shed")
        assert c.value(outcome="ok") == 1 and c.value(outcome="shed") == 2
        # get-or-create: same object back, counts shared
        assert reg.counter("reqs_total", label_names=("outcome",)) is c
        g = reg.gauge("depth")
        g.set(7)
        g.inc(-2)
        assert g.value() == 5
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("reqs_total")
        with pytest.raises(ValueError, match="labels"):
            reg.counter("reqs_total", label_names=("other",))
        with pytest.raises(ValueError, match="labels"):
            c.inc(bogus="x")

    def test_histogram_buckets_cumulative(self):
        reg = obs.MetricsRegistry()
        h = reg.histogram("lat_s", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        (sample,) = h.samples()
        assert sample["count"] == 4
        assert sample["buckets"] == {"0.01": 1, "0.1": 2, "1": 3,
                                     "+Inf": 4}
        assert sample["sum"] == pytest.approx(5.555)

    def test_histogram_percentile_is_the_shared_percentile(self):
        """Satellite: ONE quantile implementation. The histogram's
        reservoir percentile must agree exactly with
        utils.profiling.percentile over the same raw values."""
        reg = obs.MetricsRegistry()
        h = reg.histogram("lat_s", reservoir=256)
        values = [0.001 * (i ** 1.3) for i in range(1, 101)]
        for v in values:
            h.observe(v)
        for q in (50, 90, 99):
            assert h.percentile(q) == pytest.approx(percentile(values, q))

    def test_steptimer_mirrors_into_histogram(self):
        reg = obs.MetricsRegistry()
        h = reg.histogram("step_s")
        t = StepTimer(histogram=h)
        for _ in range(5):
            with t.measure():
                pass
        assert h.count() == 5
        assert h.percentile(90) == pytest.approx(
            percentile(t.durations, 90))
        assert t.p90 == pytest.approx(percentile(t.durations, 90))

    def test_serve_metrics_percentiles_from_histogram(self):
        """ServeMetrics latency tails are registry-histogram-backed and
        still agree with a direct percentile over the raw latencies."""
        reg = obs.MetricsRegistry()
        m = ServeMetrics(registry=reg)
        lats = [0.01 * i for i in range(1, 42)]
        for lat in lats:
            m.record_served(32, lat)
        snap = m.snapshot()["latency_by_bucket"]["32"]
        assert snap["count"] == len(lats)
        for q, key in ((50, "p50_s"), (90, "p90_s"), (99, "p99_s")):
            assert snap[key] == pytest.approx(percentile(lats, q))
        # the process-wide mirror saw the same stream
        mirror = reg.histogram("serve_request_latency_seconds",
                               label_names=("bucket_len",))
        assert mirror.count(bucket_len=32) == len(lats)


@pytest.mark.quick
class TestExport:
    def test_flatten_arbitrary_depth(self):
        nested = {"a": 1, "b": {"c": 2, "d": {"e": {"f": 3}}}, "g": "x"}
        assert obs.flatten(nested) == {"a": 1, "b.c": 2, "b.d.e.f": 3,
                                       "g": "x"}
        assert obs.flatten({}) == {}

    def test_prometheus_text_parses(self):
        reg = obs.MetricsRegistry()
        reg.counter("folds_total", "folds done", ("bucket",)).inc(
            3, bucket=64)
        reg.gauge("queue_depth", "depth").set(2)
        h = reg.histogram("lat_s", "latency", buckets=(0.1, 1.0))
        h.observe(0.5)
        text = obs.prometheus_text(reg)
        assert 'folds_total{bucket="64"} 3' in text
        assert "# TYPE lat_s histogram" in text
        assert 'lat_s_bucket{le="+Inf"} 1' in text
        assert "lat_s_count 1" in text
        # the report tool's validator accepts what export produces
        assert obs_report.check_prometheus_text(text) == []

    def test_registry_json_and_jsonl_schema(self, tmp_path):
        reg = obs.MetricsRegistry()
        reg.counter("c_total").inc()
        blob = obs.registry_json(reg)
        assert blob["schema"] == 1
        assert blob["metrics"]["c_total"]["samples"][0]["value"] == 1
        path = tmp_path / "m.jsonl"
        with obs.JsonlExporter(str(path)) as exp:
            exp.write_registry(reg)
            exp.write({"custom": 1})
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        assert all(r["schema"] == 1 for r in recs)

    def test_metrics_logger_nested_depth_and_schema(self, tmp_path,
                                                    capsys):
        """Satellite: MetricsLogger handles ARBITRARY nesting (was a
        1-level special case) and stamps the shared schema version."""
        path = tmp_path / "m.jsonl"
        with MetricsLogger(str(path), stdout=True) as logger:
            logger.log(step=3, loss=0.5,
                       cache={"disk": {"deep": {"hits": 7}}, "misses": 1})
        rec = json.loads(path.read_text().splitlines()[0])
        assert rec["schema"] == 1
        assert rec["cache"]["disk"]["deep"]["hits"] == 7.0
        out = capsys.readouterr().out
        assert "cache.disk.deep.hits=7" in out and "loss=0.5" in out


@pytest.mark.quick
class TestTrace:
    def test_spans_events_and_record(self):
        tracer = obs.Tracer(slow_k=4)
        t = tracer.start_trace("req-x")
        t.begin("submit")
        t.end("submit")
        with t.span("fold", bucket_len=32):
            pass
        t.add_span("batch_form", time.monotonic(), time.monotonic())
        t.event("cache_miss")
        t.link("t99")
        t.finish("ok")
        rec = t.record()
        assert rec["schema"] == 1 and rec["status"] == "ok"
        assert [s["name"] for s in rec["spans"]] == ["submit", "fold",
                                                     "batch_form"]
        assert rec["spans"][1]["attrs"] == {"bucket_len": 32}
        assert rec["events"][0]["name"] == "cache_miss"
        assert rec["leader_trace_id"] == "t99"
        assert tracer.completed == 1 and tracer.slowest()[0] is not None

    def test_finish_idempotent_and_autoclose(self):
        tracer = obs.Tracer(slow_k=4)
        t = tracer.start_trace("r")
        t.begin("queue")           # never explicitly ended
        t.finish("shed", error="deadline expired before folding")
        t.finish("ok")             # second finish: no-op
        rec = t.record()
        assert rec["status"] == "shed"
        assert rec["error"] == "deadline expired before folding"
        (span,) = rec["spans"]
        assert span["name"] == "queue" and span["attrs"]["auto_closed"]
        assert tracer.completed == 1

    def test_slow_ring_keeps_k_slowest(self):
        tracer = obs.Tracer(slow_k=2)
        for i in range(5):
            t = tracer.start_trace(f"r{i}")
            t.add_span("fold", 0.0, 0.0)
            t._t0 -= i * 0.1       # synthetic duration: r4 slowest
            t.finish("ok")
        slow = tracer.slowest()
        assert [r["request_id"] for r in slow] == ["r4", "r3"]
        assert tracer.completed == 5

    def test_null_tracer_is_free_and_inert(self):
        t = obs.NULL_TRACER.start_trace("x")
        assert t is NULL_TRACE and not t.enabled
        t.begin("a")
        t.end("a")
        with t.span("fold"):
            pass
        t.event("e")
        t.finish("ok")
        assert not t.finished and obs.NULL_TRACER.slowest() == []

    def test_jsonl_sink(self, tmp_path):
        path = tmp_path / "traces.jsonl"
        with obs.Tracer(jsonl_path=str(path)) as tracer:
            for i in range(3):
                tr = tracer.start_trace(f"r{i}")
                with tr.span("fold"):
                    pass
                tr.finish("ok")
        recs, errors = obs_report.load_traces(str(path))
        assert len(recs) == 3 and not errors
        assert obs_report.check_traces(recs) == []


class _SchedulerHarness:
    """One traced stub-executor scheduler + its obs surfaces."""

    def __init__(self, tmp_path, executor=None, cache=True, **cfg_kwargs):
        self.registry = obs.MetricsRegistry()
        self.trace_path = str(tmp_path / "traces.jsonl")
        self.tracer = obs.Tracer(jsonl_path=self.trace_path, slow_k=8)
        self.metrics = ServeMetrics(registry=self.registry)
        cfg = SchedulerConfig(
            **{"max_batch_size": 2, "max_wait_ms": 10.0,
               "num_recycles": 0, **cfg_kwargs})
        self.scheduler = Scheduler(
            executor or _StubExecutor(), BucketPolicy((16,)), cfg,
            self.metrics,
            cache=FoldCache(registry=self.registry) if cache else None,
            model_tag="test", tracer=self.tracer, registry=self.registry)

    def records(self):
        self.tracer.close()
        recs, errors = obs_report.load_traces(self.trace_path)
        assert not errors
        return recs


class TestSchedulerTracing:
    def test_cache_hit_gets_complete_short_trace(self, tmp_path):
        h = _SchedulerHarness(tmp_path)
        (req,) = _requests(8)
        dup = FoldRequest(seq=req.seq.copy())
        with h.scheduler as sched:
            assert sched.submit(req).result(timeout=30).ok
            resp = sched.submit(dup).result(timeout=30)
        assert resp.source == "cache"
        by_id = {r["request_id"]: r for r in h.records()}
        hit = by_id[dup.request_id]
        assert hit["status"] == "ok" and hit["source"] == "cache"
        assert [s["name"] for s in hit["spans"]] == ["submit"]
        assert any(e["name"] == "cache_hit" for e in hit["events"])
        # the original fold's trace covers the full pipeline
        fold = by_id[req.request_id]
        names = [s["name"] for s in fold["spans"]]
        assert names[:2] == ["submit", "queue"]
        assert "fold" in names and "writeback" in names

    def test_follower_trace_links_to_leader(self, tmp_path):
        h = _SchedulerHarness(tmp_path,
                              executor=_StubExecutor(delay_s=0.1))
        (req,) = _requests(8)
        dup = FoldRequest(seq=req.seq.copy())
        with h.scheduler as sched:
            t_lead = sched.submit(req)
            t_foll = sched.submit(dup)
            assert t_lead.result(timeout=30).ok
            assert t_foll.result(timeout=30).source == "coalesced"
        by_id = {r["request_id"]: r for r in h.records()}
        leader, follower = by_id[req.request_id], by_id[dup.request_id]
        assert follower["leader_trace_id"] == leader["trace_id"]
        assert follower["source"] == "coalesced"
        assert any(e["name"] == "coalesced" for e in follower["events"])
        assert "parked" in [s["name"] for s in follower["spans"]]

    def test_shed_trace_carries_reason(self, tmp_path):
        h = _SchedulerHarness(tmp_path, cache=False)
        (req,) = _requests(8)
        req.deadline_s = 0.0
        with h.scheduler as sched:
            resp = sched.submit(req).result(timeout=30)
        assert resp.status == "shed"
        (rec,) = h.records()
        assert rec["status"] == "shed"
        assert "deadline expired" in rec["error"]

    def test_follower_own_deadline_enforced(self, tmp_path):
        """Satellite: a parked follower whose deadline passes is shed
        with its OWN terminal state (follower_deadline_exceeded) while
        the leader keeps folding."""
        # leader can never batch (huge wait/batch): follower must time
        # out on its own
        h = _SchedulerHarness(tmp_path, max_batch_size=8,
                              max_wait_ms=60_000.0, poll_ms=5.0)
        (req,) = _requests(8)
        dup = FoldRequest(seq=req.seq.copy(), deadline_s=0.05)
        sched = h.scheduler.start()
        t_lead = sched.submit(req)
        t_foll = sched.submit(dup)
        resp = t_foll.result(timeout=10)
        assert resp.status == "shed" and resp.source == "coalesced"
        assert "follower_deadline_exceeded" in resp.error
        assert not t_lead.done()        # leader unaffected, still queued
        sched.stop(drain=True)          # leader folds on drain
        assert t_lead.result(timeout=10).ok
        assert h.registry.counter(
            "serve_follower_deadline_exceeded_total").value() == 1
        assert h.metrics.snapshot()["shed"] == 1
        by_id = {r["request_id"]: r for r in h.records()}
        foll_rec = by_id[dup.request_id]
        assert foll_rec["status"] == "shed"
        assert any(e["name"] == "follower_deadline_exceeded"
                   for e in foll_rec["events"])
        assert by_id[req.request_id]["status"] == "ok"

    def test_every_terminal_state_exactly_one_complete_trace(
            self, tmp_path):
        """Acceptance: fold / cache / coalesced / shed / error each
        yield exactly one complete trace covering submit->terminal, and
        the obs_report tripwire passes over the emitted JSONL."""
        h = _SchedulerHarness(tmp_path,
                              executor=_StubExecutor(delay_s=0.05))
        reqs = _requests(8, 12)
        dup_coalesce = FoldRequest(seq=reqs[0].seq.copy())
        dup_cache = FoldRequest(seq=reqs[1].seq.copy())
        shed_req = _requests(10, seed=1)[0]
        shed_req.deadline_s = 0.0
        with h.scheduler as sched:
            t0 = sched.submit(reqs[0])
            tc = sched.submit(dup_coalesce)          # -> coalesced
            t1 = sched.submit(reqs[1])
            for t in (t0, tc, t1):
                t.result(timeout=30)
            th = sched.submit(dup_cache)             # -> cache hit
            ts = sched.submit(shed_req)              # -> shed
            th.result(timeout=30)
            ts.result(timeout=30)
        # error terminal: a second scheduler whose executor raises
        boom = _SchedulerHarness(tmp_path / "boom",
                                 executor=_StubExecutor(boom=True),
                                 cache=False)
        (err_req,) = _requests(8, seed=2)
        with boom.scheduler as sched:
            err = sched.submit(err_req).result(timeout=30)
        assert err.status == "error"

        recs = h.records() + boom.records()
        all_reqs = [reqs[0], dup_coalesce, reqs[1], dup_cache, shed_req,
                    err_req]
        by_id = {}
        for rec in recs:
            assert rec["request_id"] not in by_id, "duplicate trace"
            by_id[rec["request_id"]] = rec
        assert len(by_id) == len(all_reqs)
        expect = {reqs[0].request_id: ("ok", "fold"),
                  dup_coalesce.request_id: ("ok", "coalesced"),
                  reqs[1].request_id: ("ok", "fold"),
                  dup_cache.request_id: ("ok", "cache"),
                  shed_req.request_id: ("shed", "fold"),
                  err_req.request_id: ("error", "fold")}
        for rid, (status, source) in expect.items():
            rec = by_id[rid]
            assert rec["status"] == status, rec
            assert rec["source"] == source, rec
            assert rec["spans"][0]["name"] == "submit"
        # the smoke tripwire agrees: complete, schema'd, no orphans
        assert obs_report.check_traces(recs) == []
        stats = obs_report.stage_stats(recs)
        assert stats["fold"]["count"] == 2     # two real batches folded
        assert obs_report.render_waterfall(stats)

    def test_serve_stats_exposes_slowest_traces(self, tmp_path):
        h = _SchedulerHarness(tmp_path, cache=False,
                              executor=_StubExecutor(delay_s=0.02))
        with h.scheduler as sched:
            for r in _requests(8, 12, 9):
                sched.submit(r).result(timeout=30)
            stats = sched.serve_stats()
        assert stats["traces"], "slow-trace ring empty"
        assert all(t["status"] == "ok" for t in stats["traces"])
        assert stats["traces"][0]["duration_s"] == max(
            t["duration_s"] for t in stats["traces"])

    def test_untraced_scheduler_unchanged(self):
        """No tracer -> NULL_TRACER: serving works, no traces, zero
        obs residue in responses."""
        reg = obs.MetricsRegistry()
        sched = Scheduler(_StubExecutor(), BucketPolicy((16,)),
                          SchedulerConfig(max_batch_size=2,
                                          max_wait_ms=10.0,
                                          num_recycles=0),
                          ServeMetrics(registry=reg), registry=reg)
        with sched:
            resp = sched.submit(_requests(8)[0]).result(timeout=30)
        assert resp.ok
        assert sched.serve_stats()["traces"] == []


@pytest.mark.quick
class TestObsReportTool:
    def test_check_flags_orphans_and_missing_schema(self):
        good = {"schema": 1, "trace_id": "t1", "request_id": "r1",
                "status": "ok", "source": "fold", "duration_s": 1.0,
                "spans": [{"name": "fold", "start_s": 0.1,
                           "dur_s": 0.5}], "events": []}
        assert obs_report.check_traces([good]) == []
        no_schema = dict(good, schema=None)
        unfinished = dict(good, status=None)
        orphan = dict(good, spans=[{"name": "fold", "start_s": 0.5,
                                    "dur_s": 2.0}])
        foldless = dict(good, spans=[])
        problems = obs_report.check_traces(
            [no_schema, unfinished, orphan, foldless])
        assert len(problems) == 4
        assert "schema" in problems[0]
        assert "incomplete" in problems[1]
        assert "escapes" in problems[2]
        assert "no non-zero fold span" in problems[3]

    def test_prometheus_validator_rejects_garbage(self):
        assert obs_report.check_prometheus_text("") != []
        assert obs_report.check_prometheus_text("what even is this") != []
        ok = '# TYPE x counter\nx{a="b"} 1\n'
        assert obs_report.check_prometheus_text(ok) == []

    def test_main_check_roundtrip(self, tmp_path, capsys):
        path = tmp_path / "t.jsonl"
        with obs.Tracer(jsonl_path=str(path)) as tracer:
            tr = tracer.start_trace("r0")
            with tr.span("fold"):
                time.sleep(0.001)
            tr.finish("ok")
        prom = tmp_path / "m.prom"
        reg = obs.MetricsRegistry()
        reg.counter("x_total").inc()
        obs.write_prometheus(str(prom), reg)
        assert obs_report.main([str(path), "--check",
                                "--prom", str(prom)]) == 0
        assert obs_report.main([str(path), "--json"]) == 0
        out = capsys.readouterr().out
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["traces"] == 1 and not summary["problems"]
        # a corrupt file fails the tripwire
        path.write_text('{"schema": 99, "spans": []}\n')
        assert obs_report.main([str(path), "--check"]) == 1
