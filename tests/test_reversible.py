"""Reversible-trunk tests: coupling inversion exactness, gradient parity
with the plain (autodiff-through-scan) computation of the same math, and
model-level reversible=True smoke + backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.model.reversible import (
    ReversibleEvoformer,
    _layer_fwd,
    _layer_inv,
    _run_reversible,
    layer_cfg,
)


def make_inputs(key, b=1, n=8, m_rows=3, d=16):
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (b, n, n, d))
    m = jax.random.normal(k2, (b, m_rows, n, d))
    mask = jnp.ones((b, n), dtype=bool)
    pair_mask = mask[:, :, None] & mask[:, None, :]
    msa_mask = jnp.ones((b, m_rows, n), dtype=bool)
    return x, m, pair_mask, msa_mask


def init_trunk(depth=2, d=16, use_conv=False):
    x, m, pair_mask, msa_mask = make_inputs(jax.random.PRNGKey(0), d=d)
    kw = dict(use_conv=True, conv_seq_kernels=((3, 1), (1, 3)),
              conv_msa_kernels=((1, 3),)) if use_conv else {}
    trunk = ReversibleEvoformer(dim=d, depth=depth, heads=2, dim_head=8,
                                **kw)
    params = trunk.init(jax.random.PRNGKey(1), x, m, mask=pair_mask,
                        msa_mask=msa_mask)
    return trunk, params, (x, m, pair_mask, msa_mask)


class TestReversible:
    @pytest.mark.quick
    def test_layer_inverse_roundtrip(self):
        trunk, params, (x, m, pair_mask, msa_mask) = init_trunk(depth=1)
        stacked = params["params"]["rev_layers"]
        layer_p = jax.tree.map(lambda t: t[0], stacked)
        cfg = layer_cfg(16, 2, 8)
        streams = (x, x + 0.1, m, m - 0.1)
        mask_f = pair_mask.astype(jnp.float32)
        msa_f = msa_mask.astype(jnp.float32)
        out = _layer_fwd(cfg, layer_p, streams, mask_f, msa_f)
        back = _layer_inv(cfg, layer_p, out, mask_f, msa_f)
        for a, b in zip(back, streams):
            assert np.allclose(a, b, atol=1e-4), float(jnp.abs(a - b).max())

    def test_gradients_match_plain_autodiff(self):
        trunk, params, (x, m, pair_mask, msa_mask) = init_trunk(depth=3)
        stacked = params["params"]["rev_layers"]
        cfg = layer_cfg(16, 2, 8)
        mask_f = pair_mask.astype(jnp.float32)
        msa_f = msa_mask.astype(jnp.float32)

        def loss_rev(stacked, x, m):
            out = _run_reversible(cfg, stacked, (x, x, m, m), mask_f, msa_f)
            return sum((o ** 2).sum() for o in out)

        def loss_plain(stacked, x, m):
            def body(s, p):
                return _layer_fwd(cfg, p, s, mask_f, msa_f), None
            out, _ = jax.lax.scan(body, (x, x, m, m), stacked)
            return sum((o ** 2).sum() for o in out)

        # same forward value
        assert np.isclose(float(loss_rev(stacked, x, m)),
                          float(loss_plain(stacked, x, m)), rtol=1e-6)

        g_rev = jax.grad(loss_rev, argnums=(0, 1, 2))(stacked, x, m)
        g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(stacked, x, m)
        for tr, tp in zip(jax.tree.leaves(g_rev), jax.tree.leaves(g_plain)):
            assert np.allclose(tr, tp, atol=2e-3), \
                float(jnp.abs(tr - tp).max())

    def test_trunk_module_forward(self):
        trunk, params, (x, m, pair_mask, msa_mask) = init_trunk(depth=2)
        x2, m2 = trunk.apply(params, x, m, mask=pair_mask, msa_mask=msa_mask)
        assert x2.shape == x.shape and m2.shape == m.shape
        assert bool(jnp.isfinite(x2).all() and jnp.isfinite(m2).all())
        # trunk actually transforms the input
        assert float(jnp.abs(x2 - x).max()) > 1e-3

    def test_model_reversible_flag(self):
        model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16,
                           reversible=True)
        seq = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, 21)
        params = model.init(jax.random.PRNGKey(3), seq)
        ret = model.apply(params, seq)
        assert ret.distance.shape == (1, 8, 8, 37)

        def loss(p):
            return (model.apply(p, seq).distance ** 2).sum()

        g = jax.grad(loss)(params)
        assert all(bool(jnp.isfinite(t).all()) for t in jax.tree.leaves(g))


class TestReversibleConv:
    """The reference's reversible 'conv' block type (reversible.py:
    303-347): conv blocks join the FF couplings; the layer stays exactly
    invertible and custom-vjp grads match plain autodiff."""

    @pytest.mark.quick
    def test_conv_layer_inverse_roundtrip(self):
        trunk, params, (x, m, pair_mask, msa_mask) = init_trunk(
            depth=1, use_conv=True)
        stacked = params["params"]["rev_layers"]
        layer_p = jax.tree.map(lambda t: t[0], stacked)
        cfg = layer_cfg(16, 2, 8, use_conv=True,
                        conv_seq_kernels=((3, 1), (1, 3)),
                        conv_msa_kernels=((1, 3),))
        streams = (x, x + 0.1, m, m - 0.1)
        mask_f = pair_mask.astype(jnp.float32)
        msa_f = msa_mask.astype(jnp.float32)
        out = _layer_fwd(cfg, layer_p, streams, mask_f, msa_f)
        back = _layer_inv(cfg, layer_p, out, mask_f, msa_f)
        for a, b in zip(back, streams):
            assert np.allclose(a, b, atol=1e-4), float(jnp.abs(a - b).max())

    def test_model_reversible_conv(self):
        model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16,
                           reversible=True, use_conv=True,
                           conv_seq_kernels=((3, 1), (1, 3)),
                           conv_msa_kernels=((1, 3),))
        seq = jax.random.randint(jax.random.PRNGKey(0), (1, 16), 0, 21)
        msa = jax.random.randint(jax.random.PRNGKey(1), (1, 3, 16), 0, 21)
        params = model.init(jax.random.PRNGKey(2), seq, msa=msa)

        def loss(p):
            ret = model.apply(p, seq, msa=msa)
            return (ret.distance ** 2).mean()

        val, grads = jax.value_and_grad(loss)(params)
        assert np.isfinite(float(val))
        finite = [bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads)]
        assert all(finite)


class TestReversibleDropout:
    """Dropout through the reversible trunk (reference reversible.py:26-56
    RNG record/replay, done as deterministic fold_in key derivation)."""

    def _trunk(self, depth=2, d=16):
        x, m, pair_mask, msa_mask = make_inputs(jax.random.PRNGKey(0), d=d)
        trunk = ReversibleEvoformer(dim=d, depth=depth, heads=2,
                                    dim_head=8, attn_dropout=0.1,
                                    ff_dropout=0.1)
        params = trunk.init(jax.random.PRNGKey(1), x, m, mask=pair_mask,
                            msa_mask=msa_mask)
        return trunk, params, (x, m, pair_mask, msa_mask)

    @pytest.mark.quick
    def test_grads_match_plain_autodiff_with_dropout(self):
        """The custom_vjp (invert + replay) gradient at dropout 0.1 must
        equal plain autodiff through the same couplings with the SAME
        keys — the matched-keys gradient-parity check."""
        from alphafold2_tpu.model.reversible import _layer_keys

        trunk, params, (x, m, pair_mask, msa_mask) = self._trunk(depth=2)
        stacked = params["params"]["rev_layers"]
        cfg = layer_cfg(16, 2, 8, attn_dropout=0.1, ff_dropout=0.1)
        mask_f = pair_mask.astype(jnp.float32)
        msa_f = msa_mask.astype(jnp.float32)
        key = jax.random.PRNGKey(7)
        streams = (x, x, m, m)

        def loss_custom(p):
            out = _run_reversible(cfg, p, streams, mask_f, msa_f, key)
            return sum((o ** 2).sum() for o in out)

        def loss_naive(p):
            keys = _layer_keys(key, p)
            s = streams
            for i in range(2):
                lp = jax.tree.map(lambda t, i=i: t[i], p)
                s = _layer_fwd(cfg, lp, s, mask_f, msa_f, keys[i])
            return sum((o ** 2).sum() for o in s)

        # same masks -> identical primal values
        np.testing.assert_allclose(float(loss_custom(stacked)),
                                   float(loss_naive(stacked)), rtol=1e-5)
        g1 = jax.grad(loss_custom)(stacked)
        g2 = jax.grad(loss_naive)(stacked)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)

    def test_dropout_active_and_reproducible(self):
        from conftest import perturb_params

        trunk, params, (x, m, pair_mask, msa_mask) = self._trunk()
        # off the zero-init point, where the coupling deltas are nonzero
        params = perturb_params(params, jax.random.PRNGKey(11))
        det = trunk.apply(params, x, m, mask=pair_mask, msa_mask=msa_mask,
                          deterministic=True)
        r1 = trunk.apply(params, x, m, mask=pair_mask, msa_mask=msa_mask,
                         deterministic=False,
                         rngs={"dropout": jax.random.PRNGKey(3)})
        r1b = trunk.apply(params, x, m, mask=pair_mask, msa_mask=msa_mask,
                          deterministic=False,
                          rngs={"dropout": jax.random.PRNGKey(3)})
        r2 = trunk.apply(params, x, m, mask=pair_mask, msa_mask=msa_mask,
                         deterministic=False,
                         rngs={"dropout": jax.random.PRNGKey(4)})
        assert float(jnp.abs(r1[0] - det[0]).max()) > 1e-6  # active
        np.testing.assert_array_equal(np.asarray(r1[0]),
                                      np.asarray(r1b[0]))  # same key
        assert float(jnp.abs(r1[0] - r2[0]).max()) > 1e-6   # fresh key

    def test_attn_dropout_alone_is_active(self):
        """Regression: attn_dropout must reach the attention modules
        (it was silently inert — the blocks declared but never forwarded
        their dropout field)."""
        from conftest import perturb_params

        x, m, pair_mask, msa_mask = make_inputs(jax.random.PRNGKey(0))
        trunk = ReversibleEvoformer(dim=16, depth=1, heads=2, dim_head=8,
                                    attn_dropout=0.3, ff_dropout=0.0)
        params = perturb_params(
            trunk.init(jax.random.PRNGKey(1), x, m, mask=pair_mask,
                       msa_mask=msa_mask), jax.random.PRNGKey(2))
        det = trunk.apply(params, x, m, mask=pair_mask,
                          msa_mask=msa_mask, deterministic=True)
        sto = trunk.apply(params, x, m, mask=pair_mask,
                          msa_mask=msa_mask, deterministic=False,
                          rngs={"dropout": jax.random.PRNGKey(3)})
        assert float(jnp.abs(sto[0] - det[0]).max()) > 1e-6

    def test_evoformer_flag_lifted(self):
        """Evoformer(reversible=True, dropout>0) now trains: loss finite,
        grads nonzero, deterministic path still exact."""
        from alphafold2_tpu.model.evoformer import Evoformer

        x, m, pair_mask, msa_mask = make_inputs(jax.random.PRNGKey(0))
        ev = Evoformer(dim=16, depth=2, heads=2, dim_head=8,
                       reversible=True, attn_dropout=0.1, ff_dropout=0.1)
        params = ev.init(jax.random.PRNGKey(1), x, m, mask=pair_mask,
                         msa_mask=msa_mask)

        def loss(p, key):
            xo, mo = ev.apply(p, x, m, mask=pair_mask, msa_mask=msa_mask,
                              deterministic=False, rngs={"dropout": key})
            return (xo ** 2).sum() + (mo ** 2).sum()

        val, g = jax.value_and_grad(loss)(params, jax.random.PRNGKey(2))
        assert np.isfinite(float(val))
        assert sum(float(jnp.abs(l).sum()) for l in jax.tree.leaves(g)) > 0
