"""Networked fleet front door tests (ISSUE 6): the rpc wire format,
LocalTransport/HttpTransport semantics, the FrontDoorServer protocol
(submit -> long-poll -> terminal, 409/429/503, cancel), graceful drain,
crash-recovery persistence (quarantine JSONL + rollout epoch), the
unified health payload + breaker-aware recovery probe, scheduler-level
failover on transport death, and — `slow`-marked, excluded from
tier-1 — a real multi-process fleet surviving kill -9 and drain.

The fast tier is stub-executor + localhost HTTP, no model; only the
procfleet class spawns real replica processes (each imports jax and
compiles, seconds-to-minutes scale — serve_smoke.sh phase 6 is the
full version of that story).
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from alphafold2_tpu import fleet
from alphafold2_tpu.cache import FoldCache, fold_key
from alphafold2_tpu.fleet.frontdoor import FrontDoorServer
from alphafold2_tpu.fleet.rpc import (HttpTransport, LocalTransport,
                                      RPC_TRANSPORT_MARKER,
                                      decode_request, decode_response,
                                      encode_request, encode_response,
                                      request_headers)
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, DrainingError,
                                  FoldRequest, FoldResponse, FoldTicket,
                                  RetryPolicy, Scheduler,
                                  SchedulerConfig)
from alphafold2_tpu.serve.resilience import Quarantine

MSA_DEPTH = 3


class _OkExecutor:
    """Stub executor: deterministic coords, optional pre-run delay."""

    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s
        self.calls = 0

    def run(self, batch, num_recycles, trace=None):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.calls += 1
        b, n = batch["seq"].shape

        class R:
            coords = np.zeros((b, n, 3), np.float32)
            confidence = np.full((b, n), 0.5, np.float32)

        return R()

    def stats(self):
        return {"calls": self.calls}


class _PoisonExecutor(_OkExecutor):
    """Deterministic failure on every run — the bisection/quarantine
    path without a model."""

    def run(self, batch, num_recycles, trace=None):
        self.calls += 1
        raise ValueError("degenerate input wrecks the structure module")


def _request(seed=0, n=12, **kwargs):
    rng = np.random.default_rng(seed)
    return FoldRequest(
        seq=rng.integers(0, 20, size=n).astype(np.int32),
        msa=rng.integers(0, 20, size=(MSA_DEPTH, n)).astype(np.int32),
        **kwargs)


def _scheduler(executor=None, msa_depth=MSA_DEPTH, model_tag="fd",
               **kwargs):
    policy = BucketPolicy((16,))
    config = SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                             poll_ms=2.0, msa_depth=msa_depth)
    return Scheduler(executor or _OkExecutor(), policy, config,
                     model_tag=model_tag,
                     registry=MetricsRegistry(), **kwargs)


# -- wire format ---------------------------------------------------------

@pytest.mark.quick
class TestWireFormat:
    def test_request_roundtrip(self):
        req = _request(seed=3, priority=2, deadline_s=1.5,
                       forwarded=True)
        body = encode_request(req)
        got = decode_request(body, request_headers(req, tag="v1"))
        assert np.array_equal(got.seq, req.seq)
        assert np.array_equal(got.msa, req.msa)
        assert got.priority == 2 and got.deadline_s == 1.5
        assert got.forwarded and got.request_id == req.request_id

    def test_request_without_msa_or_deadline(self):
        req = FoldRequest(seq=np.arange(8, dtype=np.int32))
        got = decode_request(encode_request(req), request_headers(req))
        assert got.msa is None and got.deadline_s is None
        assert not got.forwarded

    def test_garbage_request_raises(self):
        with pytest.raises(ValueError):
            decode_request(b"not an npz", {})

    def test_response_roundtrip_ok_and_error(self):
        ok = FoldResponse(request_id="r1", status="ok",
                          coords=np.ones((5, 3), np.float32),
                          confidence=np.full((5,), 0.5, np.float32),
                          bucket_len=16, source="cache", attempts=3)
        body, headers = encode_response(ok)
        got = decode_response(body, headers)
        assert got.ok and got.source == "cache" and got.attempts == 3
        assert got.bucket_len == 16
        assert np.allclose(got.coords, ok.coords)

        err = FoldResponse(request_id="r2", status="poisoned",
                           error="bad\nnews")
        body, headers = encode_response(err)
        got = decode_response(body, headers)
        assert got.status == "poisoned" and "bad news" in got.error
        assert got.coords is None

    def test_ok_response_without_arrays_fails_validation(self):
        body, headers = encode_response(
            FoldResponse(request_id="r", status="error", error="x"))
        headers["X-Status"] = "ok"       # forged: ok needs arrays
        with pytest.raises(ValueError):
            decode_response(body, headers)


# -- persistence: quarantine + rollout -----------------------------------

@pytest.mark.quick
class TestQuarantinePersistence:
    def test_jsonl_roundtrip_and_strike(self, tmp_path):
        path = str(tmp_path / "q.jsonl")
        q1 = Quarantine(registry=MetricsRegistry(), path=path)
        assert q1.add("k1", reason="poison_input")
        assert not q1.strike("k2", threshold=2)   # sub-threshold
        assert q1.strike("k2", threshold=2)       # quarantined now
        q2 = Quarantine(registry=MetricsRegistry(), path=path)
        assert "k1" in q2 and "k2" in q2
        assert q2.loaded == 2
        assert q2.reason("k1") == "poison_input"
        # strikes are NOT persisted: suspicion resets with the process
        q3 = Quarantine(registry=MetricsRegistry(), path=path)
        assert not q3.strike("k3", threshold=2)

    def test_restarted_scheduler_fails_poison_fast(self, tmp_path):
        """THE crash-recovery regression: quarantine -> restart ->
        duplicate fails fast as "poisoned" with zero executor calls."""
        path = str(tmp_path / "quarantine.jsonl")
        req = _request(seed=7)
        retry = RetryPolicy(max_attempts=2, backoff_base_s=0.001,
                            backoff_max_s=0.01)
        sched1 = _scheduler(_PoisonExecutor(), model_tag="qtest",
                            retry=retry, quarantine_path=path)
        with sched1:
            resp = sched1.submit(req).result(timeout=30)
        assert resp.status == "poisoned"
        assert os.path.exists(path)

        # "restart": a fresh scheduler process state, same disk
        counting = _OkExecutor()
        sched2 = _scheduler(counting, model_tag="qtest", retry=retry,
                            quarantine_path=path)
        assert sched2._quarantine.loaded == 1
        with sched2:
            dup = FoldRequest(seq=req.seq, msa=req.msa)
            resp2 = sched2.submit(dup).result(timeout=30)
        assert resp2.status == "poisoned"
        assert counting.calls == 0       # never re-folded, never re-bisected

    def test_unreadable_path_degrades_to_memory_only(self, tmp_path):
        q = Quarantine(registry=MetricsRegistry(),
                       path=str(tmp_path / "absent" / "q.jsonl"))
        assert q.loaded == 0
        assert q.add("k")                # persists by creating the dir
        q2 = Quarantine(registry=MetricsRegistry(),
                        path=str(tmp_path / "absent" / "q.jsonl"))
        assert "k" in q2


@pytest.mark.quick
class TestRolloutPersistence:
    def test_bump_persists_and_reloads(self, tmp_path):
        path = str(tmp_path / "rollout.json")
        st = fleet.RolloutState("v1", registry=MetricsRegistry(),
                                persist_path=path)
        st.bump("v2")
        st.bump("v3")
        with open(path) as fh:
            assert json.load(fh) == {"tag": "v3", "epoch": 2}
        # restart: the persisted epoch wins over the boot default
        st2 = fleet.RolloutState("v1", registry=MetricsRegistry(),
                                 persist_path=path)
        assert st2.current() == ("v3", 2)

    def test_registry_wires_persist_path(self, tmp_path):
        path = str(tmp_path / "rollout.json")
        reg = fleet.ReplicaRegistry(model_tag="boot",
                                    registry=MetricsRegistry(),
                                    rollout_persist_path=path)
        reg.rollout.bump("rolled")
        reg2 = fleet.ReplicaRegistry(model_tag="boot",
                                     registry=MetricsRegistry(),
                                     rollout_persist_path=path)
        assert reg2.rollout.tag == "rolled"


# -- unified health ------------------------------------------------------

class TestUnifiedHealthz:
    def test_peer_healthz_carries_scheduler_truth(self):
        reg = fleet.ReplicaRegistry(model_tag="v1",
                                    registry=MetricsRegistry())
        cache = FoldCache(registry=MetricsRegistry())
        health = {"running": True, "draining": False, "queue_depth": 4,
                  "breaker": "closed", "model_tag": "v1"}
        srv = fleet.PeerCacheServer(cache, rollout=reg.rollout,
                                    replica_id="r1",
                                    metrics=MetricsRegistry(),
                                    health_source=lambda: dict(health))
        with srv:
            host, port = srv.address
            with urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=5) as resp:
                snap = json.loads(resp.read())
        assert snap["breaker"] == "closed"
        assert snap["queue_depth"] == 4
        assert snap["tag"] == "v1" and snap["replica"] == "r1"

    def test_recovery_probe_treats_open_breaker_as_down(self):
        reg = fleet.ReplicaRegistry(model_tag="v1",
                                    registry=MetricsRegistry())
        cache = FoldCache(registry=MetricsRegistry())
        health = {"breaker": "open", "running": True,
                  "draining": False}
        srv = fleet.PeerCacheServer(cache, rollout=reg.rollout,
                                    replica_id="r1",
                                    metrics=MetricsRegistry(),
                                    health_source=lambda: dict(health))
        with srv:
            reg.register("r0")
            reg.register("r1", peer_addr=srv.address)
            reg.mark("r1", up=False)
            client = fleet.PeerCacheClient(reg, "r0",
                                           rollout=reg.rollout,
                                           recovery_cooldown_s=0.01,
                                           metrics=MetricsRegistry())
            client._down["r1"] = 0.0
            client._probe_peer("r1")     # 200, but breaker=open
            assert not reg.is_healthy("r1")
            assert client.recoveries == 0
            assert "r1" in client._down  # still tracked for reprobe
            health["breaker"] = "closed"
            client._down["r1"] = 0.0
            client._probe_peer("r1")     # healthy payload now
            assert reg.is_healthy("r1")
            assert client.recoveries == 1

    def test_draining_payload_counts_as_down(self):
        assert not fleet.PeerCacheClient._probe_payload_healthy(
            json.dumps({"breaker": "closed", "draining": True,
                        "running": True}).encode())
        assert not fleet.PeerCacheClient._probe_payload_healthy(
            json.dumps({"running": False}).encode())
        assert fleet.PeerCacheClient._probe_payload_healthy(
            json.dumps({"replica": "legacy", "tag": ""}).encode())
        assert fleet.PeerCacheClient._probe_payload_healthy(
            b"not json at all")


# -- front door protocol over real HTTP ----------------------------------

class _Door:
    """One scheduler + front door on an ephemeral port."""

    def __init__(self, executor=None, rollout=None, retry=None,
                 model_tag="fd"):
        self.scheduler = _scheduler(executor, model_tag=model_tag,
                                    retry=retry)
        self.server = FrontDoorServer(self.scheduler, rollout=rollout,
                                      replica_id="fd0",
                                      metrics=MetricsRegistry())

    def __enter__(self):
        self.scheduler.start()
        self.server.start()
        return self

    def __exit__(self, *exc):
        self.server.stop()
        self.scheduler.stop()


class TestFrontDoorHttp:
    def test_submit_poll_roundtrip(self):
        with _Door() as d:
            tr = HttpTransport(d.server.url,
                               metrics=MetricsRegistry())
            ticket = tr.submit(_request(seed=1))
            resp = ticket.result(timeout=30)
            assert resp.ok and resp.coords.shape == (12, 3)
            assert resp.attempts == 1

    def test_every_terminal_status_travels(self):
        # poisoned via a deterministic failure + retry policy
        retry = RetryPolicy(max_attempts=2, backoff_base_s=0.001)
        with _Door(executor=_PoisonExecutor(), retry=retry) as d:
            tr = HttpTransport(d.server.url,
                               metrics=MetricsRegistry())
            resp = tr.submit(_request(seed=2)).result(timeout=30)
            assert resp.status == "poisoned"
            assert "quarantined" in resp.error

    def test_tag_mismatch_409(self):
        rollout = fleet.RolloutState("v2", registry=MetricsRegistry())
        with _Door(rollout=rollout) as d:
            req = _request(seed=3)
            body = encode_request(req)
            headers = request_headers(req, tag="v1")   # straggler
            http_req = urllib.request.Request(
                d.server.url + "/v1/submit", data=body,
                headers=headers, method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(http_req, timeout=5)
            assert ei.value.code == 409
            # untagged externals skip the check (the fence is for
            # fleet-internal forwards, which always stamp)
            tr = HttpTransport(d.server.url,
                               metrics=MetricsRegistry())
            assert tr.submit(req).result(timeout=30).ok

    def test_unknown_ticket_404_and_single_pickup(self):
        with _Door() as d:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    d.server.url + "/v1/result/nope", timeout=5)
            assert ei.value.code == 404

    def test_draining_replica_503s_and_exits_clean(self):
        with _Door() as d:
            tr = HttpTransport(d.server.url,
                               metrics=MetricsRegistry())
            assert tr.submit(_request(seed=4)).result(timeout=30).ok
            assert d.scheduler.drain(timeout_s=5.0)
            with pytest.raises(urllib.error.HTTPError) as ei:
                tr.submit(_request(seed=5))
            assert ei.value.code == 503
            # direct (in-process) callers get the typed error
            with pytest.raises(DrainingError):
                d.scheduler.submit(_request(seed=6))
            assert d.scheduler.serve_stats()["drains"] == 1

    def test_drain_folds_pending_and_spans_mark_it(self):
        from alphafold2_tpu.obs import Tracer

        tracer = Tracer(jsonl_path=None, slow_k=8)
        sched = _scheduler(_OkExecutor(delay_s=0.05), tracer=tracer)
        server = FrontDoorServer(sched, replica_id="fd0",
                                 metrics=MetricsRegistry())
        sched.start()
        server.start()
        try:
            tr = HttpTransport(server.url, metrics=MetricsRegistry())
            tickets = [tr.submit(_request(seed=s)) for s in range(4)]
            assert sched.drain(timeout_s=30.0)
            # drain finishes in-flight work: every ticket terminal ok
            resps = [t.result(timeout=30) for t in tickets]
            assert all(r.ok for r in resps)
            drained = [rec for rec in tracer.slowest()
                       if any(s["name"] == "drain"
                              for s in rec["spans"])]
            assert drained, "no drain spans on requests caught mid-drain"
        finally:
            server.stop()
            sched.stop()

    def test_oversized_request_is_400_not_500(self):
        # a seq beyond the largest bucket is the CLIENT's error: 400,
        # so failover layers don't retry a deterministic refusal
        # across the whole fleet
        with _Door() as d:
            req = FoldRequest(seq=np.arange(64, dtype=np.int32))
            body = encode_request(req)
            http_req = urllib.request.Request(
                d.server.url + "/v1/submit", data=body,
                headers=request_headers(req), method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(http_req, timeout=5)
            assert ei.value.code == 400

    def test_fleet_client_surfaces_client_errors_without_failover(self):
        from alphafold2_tpu.fleet.procfleet import FleetClient

        with _Door() as d:
            client = FleetClient([d.server.url, d.server.url],
                                 result_timeout_s=10.0)
            with pytest.raises(urllib.error.HTTPError) as ei:
                client.fold(FoldRequest(seq=np.arange(64,
                                                      dtype=np.int32)))
            assert ei.value.code == 400
            assert client.snapshot()["submit_retries"] == 0

    def test_partition_503s_data_plane_then_heals(self):
        with _Door() as d:
            d.server.set_partition(0.3)
            tr = HttpTransport(d.server.url,
                               metrics=MetricsRegistry())
            with pytest.raises(urllib.error.HTTPError) as ei:
                tr.submit(_request(seed=7))
            assert ei.value.code == 503
            # healthz refuses too: probes must keep it marked down
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(d.server.url + "/healthz",
                                       timeout=5)
            time.sleep(0.4)              # auto-heal
            assert tr.submit(_request(seed=7)).result(timeout=30).ok

    def test_admin_rollout_and_stats(self):
        rollout = fleet.RolloutState("v1", registry=MetricsRegistry())
        with _Door(rollout=rollout) as d:
            payload = json.dumps({"tag": "v2"}).encode()
            req = urllib.request.Request(
                d.server.url + "/admin/rollout", data=payload,
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=5) as resp:
                out = json.loads(resp.read())
            assert out == {"tag": "v2", "epoch": 1}
            assert rollout.tag == "v2"
            with urllib.request.urlopen(d.server.url + "/admin/stats",
                                        timeout=5) as resp:
                stats = json.loads(resp.read())
            assert stats["running"] is True
            assert "failovers" in stats and "drains" in stats


class TestHttpTransportFailure:
    def test_submit_time_refusal_raises(self):
        tr = HttpTransport("http://127.0.0.1:9",  # discard port: dead
                           timeout_s=0.5, metrics=MetricsRegistry())
        with pytest.raises(Exception):
            tr.submit(_request(seed=1))

    def test_owner_death_midfold_resolves_transport_marker(self):
        d = _Door(executor=_OkExecutor(delay_s=1.0))
        d.scheduler.start()
        d.server.start()
        tr = HttpTransport(d.server.url, timeout_s=1.0,
                           poll_wait_s=0.1,
                           metrics=MetricsRegistry())
        ticket = tr.submit(_request(seed=8))
        d.server.stop()                  # the owner "dies" mid-fold
        resp = ticket.result(timeout=30)
        assert resp.status == "error"
        assert RPC_TRANSPORT_MARKER in resp.error
        d.scheduler.stop()

    def test_result_timeout_sends_remote_cancel(self):
        reg = MetricsRegistry()
        with _Door(executor=_OkExecutor(delay_s=0.8)) as d:
            tr = HttpTransport(d.server.url, poll_wait_s=0.05,
                               metrics=reg)
            ticket = tr.submit(_request(seed=9))
            with pytest.raises(TimeoutError):
                ticket.result(timeout=0.1)
            assert tr.cancels == 1
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if d.server.snapshot()["parked_tickets"] == 0:
                    break
                time.sleep(0.05)
            # cancelled slot freed (either at cancel or when the late
            # result hit the cancelled slot's done callback)
            assert d.server.snapshot()["parked_tickets"] == 0
        snap = reg.snapshot()
        assert snap["fleet_remote_cancels_total"]["samples"][0][
            "value"] == 1


# -- scheduler-level failover --------------------------------------------

class _DyingTransport:
    """Accepts the forward, then reports the owner died mid-fold."""

    def __init__(self):
        self.submits = 0

    def submit(self, request, trace=None):
        self.submits += 1
        ticket = FoldTicket(request.request_id)

        def _die():
            ticket._resolve(FoldResponse(
                request_id=request.request_id, status="error",
                error=f"{RPC_TRANSPORT_MARKER}: owner killed"))

        threading.Timer(0.05, _die).start()
        return ticket


class TestSchedulerFailover:
    def _routed_pair(self, transport):
        reg = fleet.ReplicaRegistry(model_tag="v1",
                                    registry=MetricsRegistry())
        reg.register("r0")
        reg.register("r1", transport=transport)
        router = fleet.ConsistentHashRouter(reg, "r0",
                                            metrics=MetricsRegistry())
        cache = FoldCache(registry=MetricsRegistry())
        sched = _scheduler(cache=cache, model_tag="v1", router=router)
        return reg, router, sched

    def _owned_by(self, sched, router, owner):
        for s in range(200):
            req = _request(seed=s)
            key = fold_key(req.seq, req.msa,
                           msa_depth=sched.config.msa_depth,
                           num_recycles=sched.config.num_recycles,
                           model_tag="v1")
            if router.owner_for(key) == owner:
                return req
        raise AssertionError("no key owned by " + owner)

    def test_dead_owner_fails_over_to_local_fold(self):
        dying = _DyingTransport()
        reg, router, sched = self._routed_pair(dying)
        with sched:
            req = self._owned_by(sched, router, "r1")
            resp = sched.submit(req).result(timeout=30)
        assert resp.ok and resp.source == "fold"
        assert dying.submits == 1
        assert sched.serve_stats()["failovers"] == 1

    def test_failover_settles_parked_followers(self):
        dying = _DyingTransport()
        reg, router, sched = self._routed_pair(dying)
        with sched:
            req = self._owned_by(sched, router, "r1")
            t0 = sched.submit(req)
            t1 = sched.submit(FoldRequest(seq=req.seq, msa=req.msa))
            a, b = t0.result(timeout=30), t1.result(timeout=30)
        assert a.ok and b.ok
        assert {a.source, b.source} == {"fold", "coalesced"}

    def test_non_transport_remote_error_stays_terminal(self):
        class _ErrTransport:
            def submit(self, request, trace=None):
                t = FoldTicket(request.request_id)
                t._resolve(FoldResponse(
                    request_id=request.request_id, status="error",
                    error="remote executor exploded"))
                return t

        reg, router, sched = self._routed_pair(_ErrTransport())
        with sched:
            req = self._owned_by(sched, router, "r1")
            resp = sched.submit(req).result(timeout=30)
        assert resp.status == "error"
        assert resp.source == "forwarded"
        assert sched.serve_stats()["failovers"] == 0

    def test_drain_waits_for_outstanding_forwards(self):
        dying = _DyingTransport()
        reg, router, sched = self._routed_pair(dying)
        sched.start()
        req = self._owned_by(sched, router, "r1")
        ticket = sched.submit(req)
        assert sched.drain(timeout_s=30.0)
        resp = ticket.result(timeout=5)
        assert resp.ok                   # failover folded during drain
        sched.stop()


# -- LocalTransport equivalence ------------------------------------------

def _scrub_timing(obj):
    """Deterministic view of serve_stats: drop wall-clock-derived
    fields (every *_s latency/TTL number and the slow-trace ring) so
    two identical runs compare byte-identical; counters, batch counts,
    padding waste, cache/router structure all stay."""
    if isinstance(obj, dict):
        return {k: _scrub_timing(v) for k, v in sorted(obj.items())
                if k != "traces" and not k.endswith("_s")}
    if isinstance(obj, list):
        return [_scrub_timing(v) for v in obj]
    return obj


@pytest.mark.quick
class TestLocalTransportEquivalence:
    def _run_workload(self, use_explicit_transport: bool) -> dict:
        """Two schedulers wired as a fleet; forwarding via an explicit
        LocalTransport vs the legacy bare-callable `submit` field must
        produce byte-identical deterministic serve_stats."""
        reg = fleet.ReplicaRegistry(model_tag="v1",
                                    registry=MetricsRegistry())
        reg.register("r0")
        reg.register("r1")
        scheds = {}
        for rid in ("r0", "r1"):
            router = fleet.ConsistentHashRouter(
                reg, rid, metrics=MetricsRegistry())
            scheds[rid] = _scheduler(
                cache=FoldCache(registry=MetricsRegistry()),
                model_tag="v1", router=router)
        for rid, s in scheds.items():
            if use_explicit_transport:
                reg.get(rid).transport = LocalTransport(s.submit)
            else:
                reg.get(rid).submit = s.submit
        for s in scheds.values():
            s.start()
        # serial closed loop: batch composition (and so every counter)
        # is deterministic, which is what lets the two wirings compare
        # byte-identical rather than merely statistically alike
        for i in range(16):              # 50% duplicates, alternating door
            req = _request(seed=i % 8)
            resp = scheds["r0" if i % 2 == 0 else "r1"].submit(
                req).result(timeout=30)
            assert resp.ok
        stats = {rid: _scrub_timing(s.serve_stats())
                 for rid, s in scheds.items()}
        for s in scheds.values():
            s.stop()
        return stats

    def test_transport_path_is_byte_identical_to_legacy(self):
        explicit = self._run_workload(use_explicit_transport=True)
        legacy = self._run_workload(use_explicit_transport=False)
        assert json.dumps(explicit, sort_keys=True) \
            == json.dumps(legacy, sort_keys=True)


# -- multi-process fleet (slow tier) -------------------------------------

@pytest.mark.slow
class TestProcFleet:
    """Real replica processes: serve_smoke.sh phase 6 in miniature.
    Each replica imports jax and compiles a tiny model — minutes-scale,
    excluded from tier-1 by the `slow` marker."""

    def test_kill_partition_drain_survival(self, tmp_path):
        from alphafold2_tpu.fleet.procfleet import (FleetClient,
                                                    ProcFleet)

        fl = ProcFleet(2, str(tmp_path / "run"),
                       model_tag="t@v1",
                       model={"dim": 16, "depth": 1, "msa_depth": 0})
        with fl:
            client = FleetClient(
                [h.frontdoor_url for h in fl.replicas],
                result_timeout_s=120.0)

            def req(seed):
                rng = np.random.default_rng(seed)
                return FoldRequest(seq=rng.integers(
                    0, 20, size=24).astype(np.int32))

            for s in range(4):
                assert client.fold(req(s), hint=s % 2).ok
            # hard kill r1: traffic fails over, restart rejoins
            fl.kill(1)
            for s in range(4, 8):
                assert client.fold(req(s), hint=s % 2).ok
            fl.restart(1)
            # rollout, then drain-restart r0: it must rejoin ROLLED
            fl.rollout("t@v2")
            assert fl.sigterm(0) == 0
            fl.restart(0)
            hz = fl.healthz(0)
            assert hz["model_tag"] == "t@v2"
            for s in range(8, 12):
                assert client.fold(req(s), hint=s % 2).ok
            # partition r1 and keep serving through r0
            fl.partition(1, 1.0)
            for s in range(12, 16):
                assert client.fold(req(s), hint=0).ok
        assert client.snapshot()["failovers"] + \
            client.snapshot()["submit_retries"] >= 1
