"""Recycle-aware iteration-level scheduling tests (ISSUE 9): step-loop
vs `lax.scan` exact numerics, the executor's init/step ExecKey
variants, scheduler early-exit/repack/streaming, preemption ordering,
the recycle_policy=None scrubbed-stats identity guard, the
converge-tol cache-key split, cache-aware parked admission, the
recycle-carry HBM pricing, MeshPolicy.parse, the ProcFleet mesh-policy
config plumbing, and the front door's progressive long-poll."""

import functools
import json
import threading
import time
from types import SimpleNamespace
from urllib import request as urlrequest

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.cache import FoldCache
from alphafold2_tpu.data.synthetic import synthetic_requests
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.predict import fold, fold_init, fold_step
from alphafold2_tpu.serve import (BucketPolicy, FoldExecutor,
                                  FoldMemoryModel, FoldRequest,
                                  MeshPolicy, QueueFullError,
                                  RecyclePolicy, Scheduler,
                                  SchedulerConfig, ServeMetrics)
from alphafold2_tpu.serve.recycle import element_deltas

MSA_DEPTH = 3


@pytest.fixture(scope="module")
def model_and_params():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1)
    n = 16
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
        mask=jnp.ones((1, n), bool),
        msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
    return model, params


def _inputs(n=16, b=2, seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.integers(0, 20, (b, n)), jnp.int32),
            jnp.asarray(rng.integers(0, 20, (b, MSA_DEPTH, n)),
                        jnp.int32),
            jnp.ones((b, n), bool),
            jnp.ones((b, MSA_DEPTH, n), bool))


def requests_of(lengths, key=1, **kwargs):
    reqs = synthetic_requests(jax.random.PRNGKey(key), num=len(lengths),
                              lengths=lengths, msa_depth=MSA_DEPTH)
    for r in reqs:
        for k, v in kwargs.items():
            setattr(r, k, v)
    return reqs


def _scheduler(model_and_params, recycle_policy=None, num_recycles=2,
               buckets=(16,), **kw):
    kw.setdefault("metrics", ServeMetrics(registry=MetricsRegistry()))
    kw.setdefault("registry", MetricsRegistry())
    ex = FoldExecutor(*model_and_params, max_entries=8)
    return Scheduler(
        ex, BucketPolicy(buckets),
        SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                        num_recycles=num_recycles, msa_depth=MSA_DEPTH),
        recycle_policy=recycle_policy, **kw)


class TestStepNumerics:
    def test_step_loop_matches_scan_exact(self, model_and_params):
        """The ISSUE 9 exactness contract, recycles 0-3: init + R
        manual steps produce coords/confidence/distogram numerically
        IDENTICAL to fold()'s compile-once lax.scan — the step body is
        the scan body, so who owns the loop cannot change what it
        computes."""
        model, params = model_and_params
        seq, msa, mask, msa_mask = _inputs()
        init_fn = jax.jit(lambda p, s, m, k, mm: fold_init(
            model, p, s, msa=m, mask=k, msa_mask=mm))
        step_fn = jax.jit(lambda p, s, rec, m, k, mm: fold_step(
            model, p, s, rec, msa=m, mask=k, msa_mask=mm))
        for num_recycles in range(4):
            ref = jax.jit(functools.partial(
                fold, model, num_recycles=num_recycles))(
                params, seq, msa=msa, mask=mask, msa_mask=msa_mask)
            state = init_fn(params, seq, msa, mask, msa_mask)
            for _ in range(num_recycles):
                state = step_fn(params, seq, state.recyclables, msa,
                                mask, msa_mask)
            np.testing.assert_array_equal(np.asarray(ref.coords),
                                          np.asarray(state.coords))
            np.testing.assert_array_equal(np.asarray(ref.confidence),
                                          np.asarray(state.confidence))
            np.testing.assert_array_equal(np.asarray(ref.distogram),
                                          np.asarray(state.distogram))

    def test_executor_step_variants(self, model_and_params):
        """init/step are distinct ExecKey variants; step keys pin the
        recycles element to 0 so ONE step executable serves every
        configured depth."""
        ex = FoldExecutor(*model_and_params, max_entries=8)
        policy = BucketPolicy((16,))
        batch, _ = policy.assemble(requests_of((8, 12)), 16, 2)
        state = ex.run_init(batch)
        ex.run_step(batch, state, 1)
        variants = {k[6] for k in ex.stats()["keys"]}
        assert variants == {"init", "step"}
        assert ex.key_for(batch, 5, variant="step")[3] == 0
        assert ex.key_for(batch, 5, variant="step") == \
            ex.key_for(batch, 2, variant="step")
        # opaque fold keys keep their recycle element and stay distinct
        assert ex.key_for(batch, 5)[3] == 5
        assert ex.key_for(batch, 5)[6] == "fold"
        # warm step reuse: a second init+step pair is all hits
        before = ex.misses
        st2 = ex.run_init(batch)
        ex.run_step(batch, st2, 1)
        assert ex.misses == before

    def test_warmup_step_mode(self, model_and_params):
        ex = FoldExecutor(*model_and_params, max_entries=8)
        fresh = ex.warmup([(16, 2, MSA_DEPTH, 3)], step_mode=True)
        assert fresh == 2                     # init + step pair
        variants = {k[6] for k in ex.stats()["keys"]}
        assert variants == {"init", "step"}

    def test_element_deltas_masks_padding(self):
        prev_c = np.zeros((2, 4, 3), np.float32)
        cur_c = np.zeros((2, 4, 3), np.float32)
        cur_c[0, 3] = 100.0                   # padding residue only
        cur_c[1, 0] = 1.0                     # real residue moved
        prev_f = np.zeros((2, 4), np.float32)
        cur_f = np.zeros((2, 4), np.float32)
        d = element_deltas(prev_c, prev_f, cur_c, cur_f, [3, 2])
        assert d[0] == 0.0                    # pad movement ignored
        assert d[1] > 0.0


class TestSchedulerStepLoop:
    def _run(self, model_and_params, recycle_policy, num_recycles=2,
             lengths=(12, 12, 12, 12)):
        sched = _scheduler(model_and_params, recycle_policy,
                           num_recycles=num_recycles)
        reqs = requests_of(lengths, key=3)
        with sched:
            tickets = [sched.submit(FoldRequest(seq=r.seq, msa=r.msa))
                       for r in reqs]
            out = [t.result(timeout=300) for t in tickets]
        return sched, tickets, out

    def test_tol0_byte_identical_to_opaque(self, model_and_params):
        """converge_tol=0 runs every configured recycle through the
        step loop and must serve EXACTLY the opaque lax.scan results
        end to end (the whole-serving-path version of the exactness
        test above)."""
        _, _, base = self._run(model_and_params, None)
        _, _, stepped = self._run(model_and_params,
                                  RecyclePolicy(converge_tol=0.0))
        for a, b in zip(base, stepped):
            assert a.ok and b.ok, (a.status, b.status, b.error)
            np.testing.assert_array_equal(a.coords, b.coords)
            np.testing.assert_array_equal(a.confidence, b.confidence)
            assert a.recycles is None
            assert b.recycles == 2

    def test_early_exit_skips_recycles(self, model_and_params):
        sched, _, out = self._run(
            model_and_params,
            RecyclePolicy(converge_tol=1e9), num_recycles=3)
        assert all(r.ok and r.recycles == 1 for r in out)
        rec = sched.serve_stats()["recycle"]
        assert rec["recycles_skipped"] > 0
        assert rec["retired_early"] == len(out)
        # batch-level steps executed < the opaque equivalent
        assert rec["recycles_executed"] < \
            sched.serve_stats()["batches"] * 3

    def test_min_recycles_floor(self, model_and_params):
        sched, _, out = self._run(
            model_and_params,
            RecyclePolicy(converge_tol=1e9, min_recycles=2),
            num_recycles=3)
        assert all(r.ok and r.recycles == 2 for r in out)

    def test_repack_survivor_batch(self, model_and_params):
        """A mixed batch where only some elements converge: survivors
        are re-packed and still serve the same results the opaque path
        produces for the full recycle count. Convergence is injected
        per-element via a tol between the two elements' actual
        deltas — measured first, so the test tracks the model instead
        of hardcoding magic numbers."""
        model, params = model_and_params
        reqs = requests_of((12, 10), key=5)
        # measure both elements' recycle-1 deltas at the SERVING shape
        # (one bucket-16 batch-2 init+step pair — the same compiled
        # programs every scheduler below uses) to pick a tol that
        # retires exactly the smaller-delta element
        ex = FoldExecutor(model, params, max_entries=8)
        batch, _ = BucketPolicy((16,)).assemble(reqs, 16, 2)
        st0 = ex.run_init(batch)
        st1 = ex.run_step(batch, st0, 1)
        deltas = element_deltas(
            np.asarray(st0.coords), np.asarray(st0.confidence),
            np.asarray(st1.coords), np.asarray(st1.confidence),
            [r.length for r in reqs])
        lo, hi = sorted(deltas)
        if not lo < hi:
            pytest.skip("degenerate model: equal per-element deltas")
        tol = (lo + hi) / 2.0
        sched = _scheduler(model_and_params,
                           RecyclePolicy(converge_tol=tol),
                           num_recycles=3)
        with sched:
            tickets = [sched.submit(FoldRequest(seq=r.seq, msa=r.msa))
                       for r in reqs]
            out = [t.result(timeout=300) for t in tickets]
        by_delta = dict(zip(deltas, out))
        assert by_delta[lo].recycles == 1          # retired first
        # the survivor outlived recycle 1 (it may still converge at a
        # later step — deltas shrink as recycling converges)
        hi_recycles = by_delta[hi].recycles
        assert hi_recycles is not None and hi_recycles > 1
        assert sched.serve_stats()["recycle"]["retired_early"] >= 1
        # the SURVIVOR was re-packed to row 0 and kept folding: its
        # result must be exactly the full step loop's at the same
        # recycle count (rows are independent through the model, so
        # row position cannot change row-wise math)
        base_sched = _scheduler(model_and_params,
                                RecyclePolicy(converge_tol=0.0),
                                num_recycles=hi_recycles)
        with base_sched:
            base = [base_sched.submit(
                FoldRequest(seq=r.seq, msa=r.msa)).result(timeout=300)
                for r in reqs]
        np.testing.assert_array_equal(by_delta[hi].coords,
                                      base[deltas.index(hi)].coords)

    def test_progressive_stream(self, model_and_params):
        sched = _scheduler(model_and_params,
                           RecyclePolicy(converge_tol=0.0, stream=True),
                           num_recycles=2)
        req = requests_of((12,), key=7)[0]
        seen = []
        with sched:
            ticket = sched.submit(FoldRequest(seq=req.seq, msa=req.msa))
            ticket.add_progress_callback(lambda p: seen.append(p))
            resp = ticket.result(timeout=300)
        assert resp.ok
        updates = ticket.progress()
        assert [p.recycle for p in updates] == [0, 1, 2, 2]
        assert updates[-1].converged
        np.testing.assert_array_equal(updates[-1].coords, resp.coords)
        np.testing.assert_array_equal(updates[-1].confidence,
                                      resp.confidence)
        assert len(seen) == len(updates)    # callback saw every update
        for p in updates:
            assert p.coords.shape == (req.length, 3)

    def test_recycle_policy_none_stats_byte_identical(
            self, model_and_params):
        """The off switch: recycle_policy=None must leave scrubbed
        serve_stats() byte-identical to a scheduler that has never
        heard of recycle scheduling (same scrub discipline as the mesh
        and transport equivalence tests)."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        def run_one(**kw):
            sched = _scheduler(model_and_params, num_recycles=1, **kw)
            reqs = requests_of((12, 8), key=9)
            with sched:
                for r in reqs:
                    assert sched.submit(
                        FoldRequest(seq=r.seq, msa=r.msa)).result(
                            timeout=300).ok
            return scrub(sched.serve_stats())

        explicit_off = run_one(recycle_policy=None)
        never_heard = run_one()
        assert json.dumps(explicit_off, sort_keys=True, default=str) \
            == json.dumps(never_heard, sort_keys=True, default=str)
        assert "recycle" not in never_heard


class TestCacheKeySplit:
    def test_converge_tol_splits_fold_key(self, model_and_params):
        """ISSUE 9 satellite fix: an early-exited result must never be
        served to a caller demanding fixed full recycles — a
        result-affecting policy keys under its own extras; tol-0 and
        policy-off keys stay shared (and offline-compatible)."""
        req = FoldRequest(seq=np.arange(12) % 20,
                          msa=(np.arange(36) % 20).reshape(3, 12))
        off = _scheduler(model_and_params, None)
        tol0 = _scheduler(model_and_params,
                          RecyclePolicy(converge_tol=0.0))
        tol = _scheduler(model_and_params,
                         RecyclePolicy(converge_tol=0.5))
        tol2 = _scheduler(model_and_params,
                          RecyclePolicy(converge_tol=0.25))
        assert off._cache_key_for(req) == tol0._cache_key_for(req)
        assert off._cache_key_for(req) != tol._cache_key_for(req)
        assert tol._cache_key_for(req) != tol2._cache_key_for(req)

    def test_early_exit_result_not_served_to_full_recycle_caller(
            self, model_and_params):
        """End to end: a store populated by an early-exit scheduler
        misses for a policy-off scheduler sharing the same cache."""
        cache = FoldCache(registry=MetricsRegistry())
        early = _scheduler(model_and_params,
                           RecyclePolicy(converge_tol=1e9),
                           num_recycles=2, cache=cache, model_tag="v1")
        req = requests_of((12,), key=11)[0]
        with early:
            resp = early.submit(
                FoldRequest(seq=req.seq, msa=req.msa)).result(timeout=300)
        assert resp.ok and resp.recycles == 1
        strict = _scheduler(model_and_params, None, num_recycles=2,
                            cache=cache, model_tag="v1")
        with strict:
            again = strict.submit(
                FoldRequest(seq=req.seq, msa=req.msa)).result(timeout=300)
        assert again.ok
        assert again.source == "fold"      # NOT a cache hit
        assert again.recycles is None


class TestParkedAdmission:
    def _sched(self, model_and_params, budget):
        ex = FoldExecutor(*model_and_params, max_entries=4)
        # worker can't form a batch (huge max_wait + max_batch), so the
        # leader parks in pending and holds queue depth at the limit
        return Scheduler(
            ex, BucketPolicy((16,)),
            SchedulerConfig(max_batch_size=8, max_wait_ms=60_000.0,
                            queue_limit=1, full_policy="reject",
                            num_recycles=0, msa_depth=MSA_DEPTH,
                            parked_bytes_budget=budget),
            metrics=ServeMetrics(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
            cache=FoldCache(registry=MetricsRegistry()),
            model_tag="v1")

    def test_duplicate_admitted_past_full_queue(self, model_and_params):
        sched = self._sched(model_and_params, budget=1 << 20)
        req = requests_of((8,), key=13)[0]
        sched.start()
        leader = sched.submit(FoldRequest(seq=req.seq, msa=req.msa))
        # duplicate of the in-flight leader: admitted as follower even
        # though the queue is at its limit
        dup = sched.submit(FoldRequest(seq=req.seq.copy(),
                                       msa=req.msa.copy()))
        # novel content still honors the bound
        novel = requests_of((10,), key=14)[0]
        with pytest.raises(QueueFullError):
            sched.submit(FoldRequest(seq=novel.seq, msa=novel.msa))
        stats = sched.serve_stats()
        assert stats["cache"]["parked_admits"] == 1
        assert stats["cache"]["parked_admit_bytes"] > 0
        sched.stop(drain=True)          # folds the leader, settles dup
        assert leader.result(timeout=120).ok
        dresp = dup.result(timeout=120)
        assert dresp.ok and dresp.source == "coalesced"
        # budget bytes released on settle
        assert sched.serve_stats()["cache"]["parked_admit_bytes"] == 0

    def test_budget_exhausted_rejects(self, model_and_params):
        sched = self._sched(model_and_params, budget=4)   # < any seq
        req = requests_of((8,), key=13)[0]
        sched.start()
        leader = sched.submit(FoldRequest(seq=req.seq, msa=req.msa))
        with pytest.raises(QueueFullError):
            sched.submit(FoldRequest(seq=req.seq.copy(),
                                     msa=req.msa.copy()))
        assert sched.serve_stats()["cache"]["parked_admits"] == 0
        sched.stop(drain=True)
        assert leader.result(timeout=120).ok

    def test_off_by_default(self, model_and_params):
        sched = self._sched(model_and_params, budget=0)
        req = requests_of((8,), key=13)[0]
        sched.start()
        leader = sched.submit(FoldRequest(seq=req.seq, msa=req.msa))
        with pytest.raises(QueueFullError):
            sched.submit(FoldRequest(seq=req.seq.copy(),
                                     msa=req.msa.copy()))
        sched.stop(drain=True)
        assert leader.result(timeout=120).ok


class _StepStub:
    """Step-capable executor stub with event choreography: the FIRST
    run_init of the long bucket blocks until the test has submitted
    the deadline request, so the preemption gap deterministically has
    urgent pending work."""

    def __init__(self, block_bucket_len):
        self.block_bucket_len = block_bucket_len
        self.started = threading.Event()
        self.release = threading.Event()
        self._blocked_once = False
        self.calls = []
        self._lock = threading.Lock()

    def _state(self, batch):
        b, n = batch["seq"].shape
        return SimpleNamespace(
            coords=np.zeros((b, n, 3), np.float32),
            confidence=np.zeros((b, n), np.float32),
            recyclables=None)

    def run_init(self, batch, trace=None, devices=None,
                 mesh_shape=None):
        n = batch["seq"].shape[1]
        with self._lock:
            self.calls.append(("init", n))
            first_block = (n == self.block_bucket_len
                           and not self._blocked_once)
            if first_block:
                self._blocked_once = True
        if first_block:
            self.started.set()
            assert self.release.wait(timeout=60)
        return self._state(batch)

    def run_step(self, batch, state, recycle_index, trace=None,
                 devices=None, mesh_shape=None):
        with self._lock:
            self.calls.append(("step", batch["seq"].shape[1],
                               recycle_index))
        time.sleep(0.01)      # a visible per-recycle cost
        return self._state(batch)

    def run(self, batch, num_recycles, **kw):       # opaque fallback
        st = self._state(batch)
        return SimpleNamespace(coords=st.coords,
                               confidence=st.confidence)

    def stats(self):
        return {"calls": len(self.calls)}


class TestPreemption:
    def test_deadline_fold_lands_between_recycles(self):
        """ISSUE 9 preemption ordering: a tight-deadline short fold
        submitted while a long batch is mid-loop executes BETWEEN the
        long batch's recycles and resolves first."""
        stub = _StepStub(block_bucket_len=64)
        sched = Scheduler(
            stub, BucketPolicy((32, 64)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                            num_recycles=2, msa_depth=0),
            metrics=ServeMetrics(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
            recycle_policy=RecyclePolicy(converge_tol=0.0,
                                         preempt=True))
        done_order = []
        rng = np.random.default_rng(0)
        long_req = FoldRequest(seq=rng.integers(0, 20, 40))
        short_req = FoldRequest(seq=rng.integers(0, 20, 12),
                                deadline_s=30.0)
        sched.start()
        try:
            t_long = sched.submit(long_req)
            t_long.add_done_callback(
                lambda r: done_order.append("long"))
            assert stub.started.wait(timeout=60)
            # the long batch is inside its first pass; the deadline
            # fold arrives NOW and must not wait out recycles 1-2
            t_short = sched.submit(short_req)
            t_short.add_done_callback(
                lambda r: done_order.append("short"))
            stub.release.set()
            r_short = t_short.result(timeout=60)
            r_long = t_long.result(timeout=60)
        finally:
            sched.stop(drain=True)
        assert r_short.ok and r_long.ok
        assert done_order == ["short", "long"]
        assert sched.serve_stats()["recycle"]["preemptions"] >= 1
        # the short batch's init ran between the long batch's steps
        long_steps = [i for i, c in enumerate(stub.calls)
                      if c[0] == "step" and c[1] == 64]
        short_init = [i for i, c in enumerate(stub.calls)
                      if c[0] == "init" and c[1] == 32]
        assert short_init and long_steps
        assert short_init[0] < long_steps[-1]

    def test_no_preempt_flag_respected(self):
        stub = _StepStub(block_bucket_len=64)
        sched = Scheduler(
            stub, BucketPolicy((32, 64)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                            num_recycles=2, msa_depth=0),
            metrics=ServeMetrics(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
            recycle_policy=RecyclePolicy(converge_tol=0.0,
                                         preempt=False))
        rng = np.random.default_rng(0)
        sched.start()
        try:
            t_long = sched.submit(FoldRequest(seq=rng.integers(0, 20, 40)))
            assert stub.started.wait(timeout=60)
            t_short = sched.submit(FoldRequest(seq=rng.integers(0, 20, 12),
                                               deadline_s=30.0))
            stub.release.set()
            assert t_long.result(timeout=60).ok
            assert t_short.result(timeout=60).ok
        finally:
            sched.stop(drain=True)
        assert sched.serve_stats()["recycle"]["preemptions"] == 0


class TestCarryPricing:
    def test_carry_adds_bytes_and_shards_like_pair(self):
        mem = FoldMemoryModel(param_bytes=0, dim=64, heads=4)
        plain = mem.fold_bytes(256, 2, 3)
        carry = mem.fold_bytes(256, 2, 3, carry_recyclables=True)
        assert carry > plain
        # the carried pairwise term shards over the slice
        carry4 = mem.fold_bytes(256, 2, 3, chips=4,
                                carry_recyclables=True)
        plain4 = mem.fold_bytes(256, 2, 3, chips=4)
        assert carry4 - plain4 < carry - plain

    def test_admits_flips_under_carry(self):
        mem = FoldMemoryModel(param_bytes=0, dim=64, heads=4)
        L, B, M = 256, 2, 3
        base = mem.fold_bytes(L, B, M)
        with_carry = mem.fold_bytes(L, B, M, carry_recyclables=True)
        mem.hbm_bytes_per_device = (base + with_carry) // 2
        pol = MeshPolicy({L: 1}, devices=[0], memory=mem)
        assert pol.admits(L, B, M)
        assert not pol.admits(L, B, M, carry_recyclables=True)

    def test_from_model_sizes_slices_for_carry(self, model_and_params):
        """`--mesh-policy auto` + step mode must SIZE for the carry it
        will later price at admission: a bucket whose opaque fold just
        fits n chips gets the bigger slice instead of being auto-sized
        into a guaranteed "too_large"."""
        model, params = model_and_params
        from alphafold2_tpu.serve.meshpolicy import FoldMemoryModel \
            as FMM
        mem = FMM.from_model(model, params)
        L, B = 512, 2
        plain = mem.fold_bytes(L, B, MSA_DEPTH, chips=1)
        carry = mem.fold_bytes(L, B, MSA_DEPTH, chips=1,
                               carry_recyclables=True)
        hbm_gb = ((plain + carry) / 2) / (1 << 30)
        kw = dict(max_batch=B, msa_depth=MSA_DEPTH, hbm_gb=hbm_gb,
                  devices=list(range(8)))
        base_pol = MeshPolicy.from_model(
            model, params, BucketPolicy((L,)), **kw)
        carry_pol = MeshPolicy.from_model(
            model, params, BucketPolicy((L,)),
            carry_recyclables=True, **kw)
        assert base_pol.chips_for(L) == 1       # opaque fold fits solo
        assert carry_pol.chips_for(L) > 1       # carry needs the shard
        # and what it sized, it admits
        assert carry_pol.admits(L, B, MSA_DEPTH,
                                carry_recyclables=True)


class TestMeshPolicyParse:
    def test_parse_forms(self):
        assert MeshPolicy.parse("") is None
        pol = MeshPolicy.parse("32=1,64=4", devices=list(range(8)))
        assert pol.shape_for(32) == (1, 1)
        assert pol.shape_for(64) == (2, 2)
        with pytest.raises(ValueError, match="bad --mesh-policy"):
            MeshPolicy.parse("32:1", devices=[0])
        with pytest.raises(ValueError, match="auto needs"):
            MeshPolicy.parse("auto")

    def test_procfleet_config_carries_mesh_policy(self, tmp_path):
        """ISSUE 9 satellite (PR-7 ROADMAP item): ProcFleet threads the
        per-replica mesh policy spec into every replica config, which
        replica_main parses at boot. Config-level test — no process
        spawn."""
        from alphafold2_tpu.fleet.procfleet import ProcFleet

        fleet = ProcFleet(2, str(tmp_path), mesh_policy="32=1,64=4",
                          mesh_hbm_gb=8.0)
        for h in fleet.replicas:
            cfg = json.load(open(h.config_path))
            assert cfg["mesh_policy"] == "32=1,64=4"
            assert cfg["mesh_hbm_gb"] == 8.0


class TestFrontDoorProgress:
    def test_progress_long_poll(self):
        """The existing long-poll exposes progressive results: before
        terminal, `?progress=1` returns 206 + the latest per-recycle
        coords with X-Recycle; the terminal 200 still follows."""
        from alphafold2_tpu.fleet.frontdoor import FrontDoorServer
        from alphafold2_tpu.fleet.rpc import encode_request
        from alphafold2_tpu.serve.request import (FoldProgress,
                                                  FoldResponse,
                                                  FoldTicket)

        tickets = {}

        class FakeScheduler:
            def submit(self, request):
                t = FoldTicket(request.request_id)
                tickets[request.request_id] = t
                return t

        fd = FrontDoorServer(FakeScheduler(), replica_id="t")
        with fd:
            req = FoldRequest(seq=np.arange(8) % 20)
            body = encode_request(req)
            post = urlrequest.Request(
                fd.url + "/v1/submit", data=body,
                headers={"X-Request-Id": req.request_id,
                         "Content-Type": "application/octet-stream"},
                method="POST")
            with urlrequest.urlopen(post, timeout=10) as resp:
                ticket_id = json.loads(resp.read())["ticket"]
            ticket = tickets[req.request_id]
            url = (f"{fd.url}/v1/result/{ticket_id}"
                   f"?wait_s=0&progress=1")
            # no progress yet: plain 204
            with urlrequest.urlopen(url, timeout=10) as resp:
                assert resp.status == 204
            coords = np.arange(24, dtype=np.float32).reshape(8, 3)
            conf = np.linspace(0, 1, 8).astype(np.float32)
            ticket._publish_progress(FoldProgress(
                req.request_id, recycle=1, coords=coords,
                confidence=conf))
            with urlrequest.urlopen(url, timeout=10) as resp:
                assert resp.status == 206
                assert resp.headers["X-Recycle"] == "1"
                assert resp.headers["X-Status"] == "running"
                import io
                with np.load(io.BytesIO(resp.read())) as z:
                    np.testing.assert_array_equal(z["coords"], coords)
            # terminal pickup unchanged, with recycles on the wire
            ticket._resolve(FoldResponse(
                request_id=req.request_id, status="ok", coords=coords,
                confidence=conf, bucket_len=8, recycles=1))
            with urlrequest.urlopen(
                    fd.url + f"/v1/result/{ticket_id}?wait_s=5",
                    timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["X-Recycles"] == "1"

    def test_rpc_roundtrip_recycles(self):
        from alphafold2_tpu.fleet.rpc import (decode_response,
                                              encode_response)
        from alphafold2_tpu.serve.request import FoldResponse

        resp = FoldResponse(
            request_id="r", status="ok",
            coords=np.zeros((4, 3), np.float32),
            confidence=np.ones(4, np.float32), bucket_len=8,
            recycles=2)
        body, headers = encode_response(resp)
        back = decode_response(body, headers)
        assert back.recycles == 2
        # a response without the field decodes to None (pre-ISSUE-9
        # peers)
        resp2 = FoldResponse(request_id="r", status="shed")
        body2, headers2 = encode_response(resp2)
        assert decode_response(body2, headers2).recycles is None
