"""Pipeline-parallelism tests: GPipe schedule over a `pipe` mesh axis
(parallel/pipeline.py) — forward/gradient exactness vs the sequential
stack, on Evoformer-block stages and on a toy affine chain.

Completes the §2.5 parallelism families (data / tensor / ZeRO / sequence
already covered); the reference's pipeline story is an empty DeepSpeed
stub (training_scripts/deepspeed.py, 0 LoC).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.model.evoformer import EvoformerBlock
from alphafold2_tpu.parallel.pipeline import (
    make_pipeline_mesh,
    microbatch,
    pipeline_apply,
    stack_stage_params,
    unmicrobatch,
)

S = 4  # stages


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_pipeline_mesh(S, 2)


class TestToyPipeline:
    def test_forward_and_grad_match_sequential(self, mesh):
        m_count = 6
        params = [{"w": jnp.float32(i + 1), "b": jnp.float32(0.1 * i)}
                  for i in range(S)]
        stacked = stack_stage_params(params)
        xs = jnp.arange(m_count * 3, dtype=jnp.float32).reshape(m_count, 3)

        def stage(p, x):
            return x * p["w"] + p["b"]

        out = pipeline_apply(stage, stacked, xs, mesh)
        ref = xs
        for p in params:
            ref = ref * p["w"] + p["b"]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5)

        g = jax.grad(lambda sp: pipeline_apply(stage, sp, xs, mesh).sum())(
            stacked)
        gr = jax.grad(lambda ps: _seq_loss(ps, xs))(params)
        np.testing.assert_allclose(
            np.asarray(g["w"]), np.asarray([p["w"] for p in gr]), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g["b"]), np.asarray([p["b"] for p in gr]), rtol=1e-5)


def _seq_loss(ps, xs):
    r = xs
    for p in ps:
        r = r * p["w"] + p["b"]
    return r.sum()


class TestEvoformerPipeline:
    def test_four_stage_evoformer_matches_sequential(self, mesh):
        b, n, msa, dim = 4, 8, 3, 32
        block = EvoformerBlock(dim=dim, heads=2, dim_head=16)
        key = jax.random.PRNGKey(0)
        kx, km, *kp = jax.random.split(key, 2 + S)
        x = jax.random.normal(kx, (b, n, n, dim), jnp.float32)
        m = jax.random.normal(km, (b, msa, n, dim), jnp.float32)
        stage_params = [block.init(k, x[:1], m[:1]) for k in kp]
        stacked = stack_stage_params(stage_params)

        def stage(p, xm):
            return block.apply(p, *xm)

        # microbatch the batch axis: 4 -> (4, 1, ...)
        xs = (microbatch(x, 4), microbatch(m, 4))
        out_x, out_m = pipeline_apply(stage, stacked, xs, mesh)
        out_x, out_m = unmicrobatch(out_x), unmicrobatch(out_m)

        ref_x, ref_m = x, m
        for p in stage_params:
            ref_x, ref_m = block.apply(p, ref_x, ref_m)

        np.testing.assert_allclose(np.asarray(out_x), np.asarray(ref_x),
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(out_m), np.asarray(ref_m),
                                   atol=2e-4)

    def test_pipeline_grads_match_sequential(self, mesh):
        b, n, msa, dim = 4, 6, 2, 16
        block = EvoformerBlock(dim=dim, heads=2, dim_head=8)
        key = jax.random.PRNGKey(1)
        kx, km, *kp = jax.random.split(key, 2 + S)
        x = jax.random.normal(kx, (b, n, n, dim), jnp.float32)
        m = jax.random.normal(km, (b, msa, n, dim), jnp.float32)
        stage_params = [block.init(k, x[:1], m[:1]) for k in kp]
        stacked = stack_stage_params(stage_params)

        def stage(p, xm):
            return block.apply(p, *xm)

        def pipe_loss(sp):
            ox, om = pipeline_apply(
                stage, sp, (microbatch(x, 4), microbatch(m, 4)), mesh)
            return (ox ** 2).mean() + (om ** 2).mean()

        def seq_loss(ps):
            rx, rm = x, m
            for p in ps:
                rx, rm = block.apply(p, rx, rm)
            return (rx ** 2).mean() + (rm ** 2).mean()

        g_pipe = jax.grad(pipe_loss)(stacked)
        g_seq = jax.grad(seq_loss)(stage_params)
        g_seq_stacked = stack_stage_params(g_seq)
        flat_p, _ = jax.tree.flatten(g_pipe)
        flat_s, _ = jax.tree.flatten(g_seq_stacked)
        for a, b_ in zip(flat_p, flat_s):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-4)
