"""Pipeline-parallelism tests: GPipe schedule over a `pipe` mesh axis
(parallel/pipeline.py) — forward/gradient exactness vs the sequential
stack, on Evoformer-block stages and on a toy affine chain.

Completes the §2.5 parallelism families (data / tensor / ZeRO / sequence
already covered); the reference's pipeline story is an empty DeepSpeed
stub (training_scripts/deepspeed.py, 0 LoC).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.model.evoformer import EvoformerBlock
from alphafold2_tpu.parallel.pipeline import (
    make_pipeline_mesh,
    microbatch,
    pipeline_apply,
    stack_stage_params,
    unmicrobatch,
)

S = 4  # stages


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_pipeline_mesh(S, 2)


class TestToyPipeline:
    def test_forward_and_grad_match_sequential(self, mesh):
        m_count = 6
        params = [{"w": jnp.float32(i + 1), "b": jnp.float32(0.1 * i)}
                  for i in range(S)]
        stacked = stack_stage_params(params)
        xs = jnp.arange(m_count * 3, dtype=jnp.float32).reshape(m_count, 3)

        def stage(p, x):
            return x * p["w"] + p["b"]

        out = pipeline_apply(stage, stacked, xs, mesh)
        ref = xs
        for p in params:
            ref = ref * p["w"] + p["b"]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5)

        g = jax.grad(lambda sp: pipeline_apply(stage, sp, xs, mesh).sum())(
            stacked)
        gr = jax.grad(lambda ps: _seq_loss(ps, xs))(params)
        np.testing.assert_allclose(
            np.asarray(g["w"]), np.asarray([p["w"] for p in gr]), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g["b"]), np.asarray([p["b"] for p in gr]), rtol=1e-5)


def _seq_loss(ps, xs):
    r = xs
    for p in ps:
        r = r * p["w"] + p["b"]
    return r.sum()


class TestEvoformerPipeline:
    def test_four_stage_evoformer_matches_sequential(self, mesh):
        b, n, msa, dim = 4, 8, 3, 32
        block = EvoformerBlock(dim=dim, heads=2, dim_head=16)
        key = jax.random.PRNGKey(0)
        kx, km, *kp = jax.random.split(key, 2 + S)
        x = jax.random.normal(kx, (b, n, n, dim), jnp.float32)
        m = jax.random.normal(km, (b, msa, n, dim), jnp.float32)
        stage_params = [block.init(k, x[:1], m[:1]) for k in kp]
        stacked = stack_stage_params(stage_params)

        def stage(p, xm):
            return block.apply(p, *xm)

        # microbatch the batch axis: 4 -> (4, 1, ...)
        xs = (microbatch(x, 4), microbatch(m, 4))
        out_x, out_m = pipeline_apply(stage, stacked, xs, mesh)
        out_x, out_m = unmicrobatch(out_x), unmicrobatch(out_m)

        ref_x, ref_m = x, m
        for p in stage_params:
            ref_x, ref_m = block.apply(p, ref_x, ref_m)

        np.testing.assert_allclose(np.asarray(out_x), np.asarray(ref_x),
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(out_m), np.asarray(ref_m),
                                   atol=2e-4)

    def test_pipeline_grads_match_sequential(self, mesh):
        b, n, msa, dim = 4, 6, 2, 16
        block = EvoformerBlock(dim=dim, heads=2, dim_head=8)
        key = jax.random.PRNGKey(1)
        kx, km, *kp = jax.random.split(key, 2 + S)
        x = jax.random.normal(kx, (b, n, n, dim), jnp.float32)
        m = jax.random.normal(km, (b, msa, n, dim), jnp.float32)
        stage_params = [block.init(k, x[:1], m[:1]) for k in kp]
        stacked = stack_stage_params(stage_params)

        def stage(p, xm):
            return block.apply(p, *xm)

        def pipe_loss(sp):
            ox, om = pipeline_apply(
                stage, sp, (microbatch(x, 4), microbatch(m, 4)), mesh)
            return (ox ** 2).mean() + (om ** 2).mean()

        def seq_loss(ps):
            rx, rm = x, m
            for p in ps:
                rx, rm = block.apply(p, rx, rm)
            return (rx ** 2).mean() + (rm ** 2).mean()

        g_pipe = jax.grad(pipe_loss)(stacked)
        g_seq = jax.grad(seq_loss)(stage_params)
        g_seq_stacked = stack_stage_params(g_seq)
        flat_p, _ = jax.tree.flatten(g_pipe)
        flat_s, _ = jax.tree.flatten(g_seq_stacked)
        for a, b_ in zip(flat_p, flat_s):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-4)


class TestModelPipeline:
    """Model-level pp (round-2 VERDICT next-round #7): the trunk's
    pipeline_stages regroups the scan-stacked params into GPipe stages
    under the mesh's pipe axis — same params tree, exactness vs the
    scanned trunk, and a full-Alphafold2 train step."""

    def _inputs(self, key, b=4, n=8, m=3, d=32):
        ks = jax.random.split(key, 2)
        x = jax.random.normal(ks[0], (b, n, n, d)) * 0.5
        msa = jax.random.normal(ks[1], (b, m, n, d)) * 0.5
        seq_mask = jnp.ones((b, n), bool).at[:, -2:].set(False)
        pmask = seq_mask[:, :, None] & seq_mask[:, None, :]
        msa_mask = jnp.ones((b, m, n), bool) & seq_mask[:, None, :]
        return x, msa, pmask, msa_mask

    def test_evoformer_pp_matches_scan(self):
        from alphafold2_tpu.model.evoformer import Evoformer
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        x, msa, pmask, msa_mask = self._inputs(jax.random.PRNGKey(60))
        kw = dict(dim=32, depth=4, heads=2, dim_head=16)
        plain = Evoformer(**kw)
        pp = Evoformer(**kw, pipeline_stages=4)
        params = plain.init(jax.random.PRNGKey(61), x, msa,
                            mask=pmask, msa_mask=msa_mask)

        xo, mo = plain.apply(params, x, msa, mask=pmask, msa_mask=msa_mask)
        mesh = make_mesh(2, 1, 1, pipe=4)
        with use_mesh(mesh):
            xp, mp = jax.jit(lambda p: pp.apply(
                p, x, msa, mask=pmask, msa_mask=msa_mask))(params)
        assert np.allclose(np.asarray(xo), np.asarray(xp), atol=2e-5)
        assert np.allclose(np.asarray(mo), np.asarray(mp), atol=2e-5)

    def test_evoformer_pp_grads_match_scan(self):
        from alphafold2_tpu.model.evoformer import Evoformer
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        x, msa, pmask, msa_mask = self._inputs(jax.random.PRNGKey(62))
        kw = dict(dim=32, depth=4, heads=2, dim_head=16)
        plain = Evoformer(**kw)
        pp = Evoformer(**kw, pipeline_stages=4)
        params = plain.init(jax.random.PRNGKey(63), x, msa,
                            mask=pmask, msa_mask=msa_mask)

        def loss(model):
            def f(p):
                xo, mo = model.apply(p, x, msa, mask=pmask,
                                     msa_mask=msa_mask)
                return (xo ** 2).sum() + (mo ** 2).sum()
            return f

        g1 = jax.grad(loss(plain))(params)
        mesh = make_mesh(2, 1, 1, pipe=4)
        with use_mesh(mesh):
            g2 = jax.jit(jax.grad(loss(pp)))(params)
        for a, b_ in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            # remat/reassociation noise under a sum-of-squares loss of
            # scale ~1e3; observed max ~2e-3 absolute on grads of |.|~1e1
            assert np.allclose(np.asarray(a), np.asarray(b_),
                               rtol=1e-4, atol=5e-3), \
                float(jnp.abs(a - b_).max())

    def test_alphafold2_pp_train_step(self):
        """Full model + train step with the pipelined trunk on a
        (pipe=2, data=2, i=2, j=1) mesh: distogram matches the non-pp
        model, loss finite, step executes."""
        from alphafold2_tpu import Alphafold2
        from alphafold2_tpu.data.synthetic import synthetic_batch
        from alphafold2_tpu.parallel import make_mesh, use_mesh
        from alphafold2_tpu.train import (TrainState, adam,
                                          make_train_step, shard_batch)

        kw = dict(dim=32, depth=2, heads=2, dim_head=16)
        plain = Alphafold2(**kw)
        pp = Alphafold2(**kw, pipeline_stages=2)
        batch = synthetic_batch(jax.random.PRNGKey(70), batch=4,
                                seq_len=8, msa_depth=3, with_coords=True)
        args = (batch["seq"],)
        bkw = dict(msa=batch["msa"], mask=batch["mask"],
                   msa_mask=batch["msa_mask"])
        params = plain.init(jax.random.PRNGKey(71), *args, **bkw)

        ret_plain = plain.apply(params, *args, **bkw)
        mesh = make_mesh(2, 2, 1, pipe=2)
        with use_mesh(mesh):
            ret_pp = jax.jit(lambda p: pp.apply(p, *args, **bkw))(params)
            assert np.allclose(np.asarray(ret_plain.distance),
                               np.asarray(ret_pp.distance), atol=2e-4)

            state = TrainState.create(apply_fn=pp.apply, params=params,
                                      tx=adam(1e-3),
                                      rng=jax.random.PRNGKey(72))
            step = jax.jit(make_train_step(pp), donate_argnums=(0,))
            new_state, metrics = step(state, shard_batch(batch, mesh))
            assert bool(jnp.isfinite(metrics["loss"]))
            assert int(new_state.step) == 1

    def test_evoformer_pp_composes_with_pair_sharding(self):
        """VERDICT r4 #4: pp x 2-D pair sharding. The pipeline shard_map
        is manual over (pipe, data) ONLY; `i`/`j` stay auto, so in-stage
        shard_pair/shard_msa constraints keep 2-D sharding the pair
        tensor. Mesh (pipe=2, i=2, j=2): exactness vs the plain trunk,
        and the compiled HLO carries both the stage-hop permutes and the
        pair re-shard collectives."""
        import re

        from alphafold2_tpu.model.evoformer import Evoformer
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        x, msa, pmask, msa_mask = self._inputs(jax.random.PRNGKey(64),
                                               b=2)
        kw = dict(dim=32, depth=4, heads=2, dim_head=16)
        plain = Evoformer(**kw)
        pp = Evoformer(**kw, pipeline_stages=2)
        params = plain.init(jax.random.PRNGKey(65), x, msa,
                            mask=pmask, msa_mask=msa_mask)
        xo, mo = plain.apply(params, x, msa, mask=pmask,
                             msa_mask=msa_mask)

        mesh = make_mesh(1, 2, 2, pipe=2)
        with use_mesh(mesh):
            f = jax.jit(lambda p: pp.apply(p, x, msa, mask=pmask,
                                           msa_mask=msa_mask))
            hlo = f.lower(params).compile().as_text()
            xp, mp = f(params)
        assert np.allclose(np.asarray(xo), np.asarray(xp), atol=2e-5)
        assert np.allclose(np.asarray(mo), np.asarray(mp), atol=2e-5)
        colls = set(re.findall(
            r"all-gather|all-to-all|collective-permute", hlo))
        assert "collective-permute" in colls      # pipeline stage hops
        assert colls & {"all-gather", "all-to-all"}  # i/j re-shards


class TestPipelineDropout:
    """Dropout through the GPipe trunk: per-(microbatch, layer) keys
    derived by fold_in ride the pipeline as raw key-data activations."""

    def test_pp_dropout_trains_and_is_keyed(self):
        from conftest import perturb_params

        from alphafold2_tpu.model.evoformer import Evoformer
        from alphafold2_tpu.parallel import make_mesh, use_mesh

        k = jax.random.PRNGKey(70)
        ks = jax.random.split(k, 2)
        b, n, m_rows, d = 4, 8, 3, 32
        x = jax.random.normal(ks[0], (b, n, n, d)) * 0.5
        msa = jax.random.normal(ks[1], (b, m_rows, n, d)) * 0.5
        pmask = jnp.ones((b, n, n), bool)
        msa_mask = jnp.ones((b, m_rows, n), bool)

        kw = dict(dim=d, depth=2, heads=2, dim_head=16,
                  attn_dropout=0.1, ff_dropout=0.1)
        pp = Evoformer(**kw, pipeline_stages=2)
        plain = Evoformer(**kw)
        params = perturb_params(
            plain.init(jax.random.PRNGKey(71), x, msa, mask=pmask,
                       msa_mask=msa_mask), jax.random.PRNGKey(72))

        mesh = make_mesh(4, 1, 1, pipe=2)
        with use_mesh(mesh):
            run = jax.jit(lambda p, key: pp.apply(
                p, x, msa, mask=pmask, msa_mask=msa_mask,
                deterministic=False, rngs={"dropout": key}))
            det = jax.jit(lambda p: pp.apply(
                p, x, msa, mask=pmask, msa_mask=msa_mask,
                deterministic=True))(params)
            r1 = run(params, jax.random.PRNGKey(1))
            r1b = run(params, jax.random.PRNGKey(1))
            r2 = run(params, jax.random.PRNGKey(2))

            # grads flow at dropout 0.1
            def loss(p, key):
                xo, mo = pp.apply(p, x, msa, mask=pmask,
                                  msa_mask=msa_mask, deterministic=False,
                                  rngs={"dropout": key})
                return (xo ** 2).sum() + (mo ** 2).sum()

            val, g = jax.jit(jax.value_and_grad(loss))(
                params, jax.random.PRNGKey(3))

        assert float(jnp.abs(r1[0] - det[0]).max()) > 1e-6   # active
        np.testing.assert_array_equal(np.asarray(r1[0]),
                                      np.asarray(r1b[0]))    # same key
        assert float(jnp.abs(r1[0] - r2[0]).max()) > 1e-6    # fresh key
        assert np.isfinite(float(val))
        assert sum(float(jnp.abs(l).sum()) for l in jax.tree.leaves(g)) > 0
