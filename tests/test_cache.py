"""Cache subsystem tests (ISSUE 2): stable digests, content-addressed
keys, the two-tier FoldCache (LRU + TTL + disk roundtrip + atomic
write / corruption quarantine), in-flight coalescing fan-out (including
leader failure propagation), the cached `fold_and_write` path, and the
end-to-end scheduler run where a 50%-duplicate workload executes only
unique work.
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2, predict, serve
from alphafold2_tpu.cache import (CachedFold, FoldCache, InflightRegistry,
                                  fold_key)
from alphafold2_tpu.data.synthetic import synthetic_requests
from alphafold2_tpu.serve import (BucketPolicy, FoldExecutor, FoldRequest,
                                  QueueFullError, Scheduler,
                                  SchedulerConfig, ServeMetrics)
from alphafold2_tpu.utils.hashing import stable_digest

MSA_DEPTH = 3


@pytest.fixture(scope="module")
def model_and_params():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1)
    n = 16
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
        mask=jnp.ones((1, n), bool),
        msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
    return model, params


def fold_result(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 3)).astype(np.float32),
            rng.uniform(size=(n,)).astype(np.float32))


@pytest.mark.quick
class TestStableDigest:
    def test_deterministic_and_type_discriminating(self):
        a = np.arange(6, dtype=np.int32)
        assert stable_digest(a) == stable_digest(a.copy())
        # dtype, shape, and scalar/str/None forms all key differently
        assert stable_digest(a) != stable_digest(a.astype(np.int64))
        assert stable_digest(a) != stable_digest(a.reshape(2, 3))
        assert stable_digest(1) != stable_digest(1.0)
        assert stable_digest(1) != stable_digest("1")
        assert stable_digest(None) != stable_digest(0)
        assert stable_digest(None) != stable_digest("")
        assert stable_digest(True) != stable_digest(1)

    def test_framing_prevents_concat_collisions(self):
        assert stable_digest("ab") != stable_digest("a", "b")
        assert stable_digest(("ab",)) != stable_digest(("a", "b"))
        assert stable_digest([1, [2, 3]]) != stable_digest([1, 2, 3])

    def test_digest_size(self):
        assert len(stable_digest("x", digest_size=4)) == 8
        assert len(stable_digest("x")) == 32

    def test_object_dtype_refused_not_pointer_hashed(self):
        """np.asarray(dict).tobytes() would hash MEMORY ADDRESSES —
        nondeterministic keys and address-reuse collisions. Must raise
        so callers fall back to not caching."""
        with pytest.raises(TypeError, match="object dtype"):
            stable_digest({"temperature": 0.1})
        with pytest.raises(TypeError):
            stable_digest({1, 2})
        with pytest.raises(TypeError):
            stable_digest(np.array([object()], dtype=object))
        with pytest.raises(TypeError):
            stable_digest((1, {"nested": True}))


@pytest.mark.quick
class TestFoldKey:
    def test_stability_and_separation(self):
        seq = np.arange(10, dtype=np.int32)
        msa = np.tile(seq, (4, 1))
        k = fold_key(seq, msa, msa_depth=2, num_recycles=1, model_tag="t")
        assert k == fold_key(seq.copy(), msa.copy(), msa_depth=2,
                             num_recycles=1, model_tag="t")
        # every config axis separates keys
        assert k != fold_key(seq, msa, msa_depth=3, num_recycles=1,
                             model_tag="t")
        assert k != fold_key(seq, msa, msa_depth=2, num_recycles=2,
                             model_tag="t")
        assert k != fold_key(seq, msa, msa_depth=2, num_recycles=1,
                             model_tag="other")
        assert k != fold_key(seq + 1, msa, msa_depth=2, num_recycles=1,
                             model_tag="t")
        assert k != fold_key(seq, msa + 1, msa_depth=2, num_recycles=1,
                             model_tag="t")

    def test_effective_msa_semantics(self):
        """Rows the server truncates away must not split the key; a
        pinned depth of 0 ignores the MSA entirely."""
        seq = np.arange(10, dtype=np.int32)
        msa = np.tile(seq, (4, 1))
        k = fold_key(seq, msa, msa_depth=2, num_recycles=0)
        assert k == fold_key(seq, msa[:2], msa_depth=2, num_recycles=0)
        assert fold_key(seq, msa, msa_depth=0, num_recycles=0) == \
            fold_key(seq, None, msa_depth=0, num_recycles=0)
        # unpinned: the full MSA contributes
        assert fold_key(seq, msa, num_recycles=0) != \
            fold_key(seq, msa[:2], num_recycles=0)

    def test_token_dtype_canonicalized(self):
        """Default-int (int64) tokens must key identically to the int32
        the server coerces to — else offline/server sharing never hits."""
        seq64 = np.arange(10)              # platform default int
        msa64 = np.tile(seq64, (2, 1))
        assert fold_key(seq64, msa64, num_recycles=0) == \
            fold_key(seq64.astype(np.int32), msa64.astype(np.int32),
                     num_recycles=0)

    def test_extras_separate_and_default_to_server_form(self):
        seq = np.arange(10, dtype=np.int32)
        base = fold_key(seq, num_recycles=0, model_tag="t")
        assert base == fold_key(seq, num_recycles=0, model_tag="t",
                                extras=None)
        assert base != fold_key(seq, num_recycles=0, model_tag="t",
                                extras=(("temperature", 0.1),))
        with pytest.raises(TypeError):
            fold_key(seq, num_recycles=0,
                     extras={"unhashable": object()})

    def test_shape_validation(self):
        with pytest.raises(ValueError, match="1-D"):
            fold_key(np.zeros((2, 3), np.int32))
        with pytest.raises(ValueError, match="msa"):
            fold_key(np.zeros(4, np.int32), np.zeros((2, 5), np.int32))


@pytest.mark.quick
class TestFoldCacheMemory:
    def test_roundtrip_and_put_copies(self):
        cache = FoldCache()
        coords, conf = fold_result()
        cache.put("k", coords, conf)
        coords[:] = -1                         # caller mutates its array...
        got = cache.get("k")
        assert isinstance(got, CachedFold)
        assert not np.array_equal(got.coords, coords)  # ...store unaffected
        assert np.array_equal(got.confidence, conf)
        assert cache.get("missing") is None
        snap = cache.snapshot()
        assert snap["hits"] == 1 and snap["misses"] == 1
        assert snap["puts"] == 1 and snap["bytes_resident"] > 0
        assert snap["hit_ratio"] == pytest.approx(1 / 2)

    def test_lru_eviction_by_entries_and_bytes(self):
        cache = FoldCache(max_entries=2)
        for i in range(3):
            cache.put(f"k{i}", *fold_result(seed=i))
        assert cache.get("k0") is None         # oldest evicted
        assert cache.get("k1") is not None and cache.get("k2") is not None
        assert cache.stats.evictions == 1 and len(cache) == 2

        one_entry = cache.get("k1").nbytes
        tight = FoldCache(max_bytes=one_entry)  # budget fits exactly one
        tight.put("a", *fold_result(seed=0))
        tight.put("b", *fold_result(seed=1))
        assert tight.get("a") is None and tight.get("b") is not None
        assert tight.bytes_resident <= one_entry

    def test_lru_order_refreshed_by_get(self):
        cache = FoldCache(max_entries=2)
        cache.put("a", *fold_result(seed=0))
        cache.put("b", *fold_result(seed=1))
        cache.get("a")                         # a becomes most-recent
        cache.put("c", *fold_result(seed=2))   # evicts b, not a
        assert cache.get("a") is not None and cache.get("b") is None

    def test_ttl_expiry_with_injected_clock(self):
        now = [100.0]
        cache = FoldCache(ttl_s=10.0, clock=lambda: now[0])
        cache.put("k", *fold_result())
        now[0] = 109.9
        assert cache.get("k") is not None
        now[0] = 110.0
        assert cache.get("k") is None          # expired == miss
        assert cache.stats.expirations == 1 and len(cache) == 0


@pytest.mark.quick
class TestFoldCacheDisk:
    def test_disk_roundtrip_across_instances(self, tmp_path):
        d = str(tmp_path / "store")
        coords, conf = fold_result()
        FoldCache(disk_dir=d).put("deadbeef01", coords, conf)
        fresh = FoldCache(disk_dir=d)          # new process, cold memory
        got = fresh.get("deadbeef01")
        assert got is not None and np.array_equal(got.coords, coords)
        assert fresh.stats.disk_hits == 1
        # promoted into memory: second get never touches disk
        fresh.get("deadbeef01")
        assert fresh.stats.disk_hits == 1 and fresh.stats.hits == 2
        # no stray tmp files from the atomic write protocol
        leftovers = [f for root, _, fs in os.walk(d) for f in fs
                     if ".tmp." in f]
        assert leftovers == []

    def test_corruption_quarantined_as_miss(self, tmp_path):
        d = str(tmp_path / "store")
        cache = FoldCache(disk_dir=d)
        cache.put("cafe0123", *fold_result())
        path = cache._path("cafe0123")
        with open(path, "wb") as fh:
            fh.write(b"this is not an npz file")

        fresh = FoldCache(disk_dir=d)
        assert fresh.get("cafe0123") is None   # miss, not an exception
        assert fresh.stats.disk_errors == 1
        assert not os.path.exists(path)
        assert os.path.exists(path + ".quarantined")
        # quarantined entries are never re-read; a recompute repopulates
        fresh.put("cafe0123", *fold_result(seed=1))
        assert FoldCache(disk_dir=d).get("cafe0123") is not None

    def test_wrong_key_content_quarantined(self, tmp_path):
        """An entry whose stored key disagrees with its filename (e.g. a
        mis-copied store) fails validation and quarantines."""
        d = str(tmp_path / "store")
        cache = FoldCache(disk_dir=d)
        cache.put("aaaa1111", *fold_result())
        os.makedirs(os.path.dirname(cache._path("bbbb2222")), exist_ok=True)
        os.rename(cache._path("aaaa1111"), cache._path("bbbb2222"))
        fresh = FoldCache(disk_dir=d)
        assert fresh.get("bbbb2222") is None
        assert fresh.stats.disk_errors == 1

    def test_disk_promotion_preserves_original_ttl(self, tmp_path):
        """Promoting a disk hit into memory must keep the ORIGINAL
        expiry (file write time + ttl), not grant a fresh lease — else
        an entry could live ~2x ttl_s by bouncing between tiers."""
        d = str(tmp_path / "store")
        now = [1000.0]
        writer = FoldCache(ttl_s=60.0, disk_dir=d, clock=lambda: now[0])
        writer.put("aa11", *fold_result())
        path = writer._path("aa11")
        os.utime(path, (950.0, 950.0))     # written at t=950
        reader = FoldCache(ttl_s=60.0, disk_dir=d, clock=lambda: now[0])
        assert reader.get("aa11") is not None   # promoted at t=1000
        now[0] = 1011.0                    # past 950 + 60
        assert reader.get("aa11") is None  # memory copy expired with it

    def test_disk_ttl_expiry(self, tmp_path):
        d = str(tmp_path / "store")
        cache = FoldCache(ttl_s=60.0, disk_dir=d)
        cache.put("feed5678", *fold_result())
        path = cache._path("feed5678")
        old = os.path.getmtime(path) - 120.0
        os.utime(path, (old, old))
        fresh = FoldCache(ttl_s=60.0, disk_dir=d)
        assert fresh.get("feed5678") is None
        assert fresh.stats.expirations == 1
        assert not os.path.exists(path)

    def test_quarantine_reconciles_memory_resident_bytes(self, tmp_path):
        """Regression (ISSUE 4): quarantining a corrupt disk entry whose
        key is ALSO memory-resident must drop the memory copy WITH its
        bytes accounting — a pop without the `bytes_resident` decrement
        would leak the byte budget until restart."""
        d = str(tmp_path / "store")
        cache = FoldCache(disk_dir=d)
        cache.put("dead0123", *fold_result())
        assert cache.bytes_resident > 0 and len(cache) == 1
        path = cache._path("dead0123")
        with open(path, "wb") as fh:
            fh.write(b"corrupt")
        # the quarantine seam every corrupt-disk discovery (get /
        # read_raw / a racing peer read) funnels through: it must
        # reconcile the memory tier, not just rename the file
        cache._quarantine(path, "dead0123")
        assert cache.stats.disk_errors == 1
        assert cache.bytes_resident == 0 and len(cache) == 0
        snap = cache.snapshot()
        assert snap["bytes_resident"] == 0
        assert snap["entries_resident"] == 0
        assert os.path.exists(path + ".quarantined")
        assert cache.read_raw("dead0123") is None   # nothing re-served

    def test_quarantine_drops_memory_copy_of_poisoned_key(self, tmp_path):
        """get() on a corrupt disk entry quarantines AND purges any
        memory-resident copy of the key, with bytes_resident reconciled
        to zero — the two tiers never disagree about a poisoned key."""
        d = str(tmp_path / "store")
        now = [1000.0]
        cache = FoldCache(ttl_s=60.0, disk_dir=d, clock=lambda: now[0])
        cache.put("f00d0001", *fold_result())
        path = cache._path("f00d0001")
        with open(path, "wb") as fh:
            fh.write(b"corrupt")
        # keep the disk file inside its TTL window under the injected
        # clock while the memory entry expires: get() then consults the
        # (corrupt) disk exactly as a restarted/TTL-churned server would
        os.utime(path, (1010.0, 1010.0))    # disk lease runs to 1070
        now[0] = 1061.0                     # memory expired, disk not
        assert cache.get("f00d0001") is None
        assert cache.stats.disk_errors == 1
        assert cache.bytes_resident == 0 and len(cache) == 0
        assert os.path.exists(path + ".quarantined")

    def test_invalidate_drops_both_tiers_with_accounting(self, tmp_path):
        d = str(tmp_path / "store")
        cache = FoldCache(disk_dir=d)
        cache.put("aa00bb11", *fold_result())
        assert cache.invalidate("aa00bb11")
        assert cache.bytes_resident == 0 and len(cache) == 0
        assert not os.path.exists(cache._path("aa00bb11"))
        assert cache.get("aa00bb11") is None
        assert not cache.invalidate("aa00bb11")   # idempotent


@pytest.mark.quick
class TestInflightRegistry:
    def test_leader_then_followers_then_settle(self):
        reg = InflightRegistry()
        assert reg.attach("k", "lead-obj") is True
        assert reg.attach("k", "f1") is False
        assert reg.attach("k", "f2") is False
        assert reg.inflight() == 1 and reg.waiting() == 2
        assert reg.settle("k") == ["f1", "f2"]
        assert reg.settle("k") == []           # settle is terminal
        assert reg.attach("k", "x") is True    # fresh leader afterwards
        snap = reg.snapshot()
        assert snap["leaders"] == 2 and snap["coalesced"] == 2


class _BoomExecutor:
    """Minimal executor stand-in that always fails."""

    def run(self, batch, num_recycles):
        raise RuntimeError("boom")

    def stats(self):
        return {"hits": 0, "misses": 0, "evictions": 0}


class TestSchedulerCoalescing:
    def _dup_requests(self, n, length=8):
        proto = synthetic_requests(jax.random.PRNGKey(3), num=1,
                                   lengths=(length,),
                                   msa_depth=MSA_DEPTH)[0]
        return [FoldRequest(seq=proto.seq, msa=proto.msa)
                for _ in range(n)]

    def test_leader_failure_propagates_to_followers(self):
        cfg = SchedulerConfig(max_batch_size=4, max_wait_ms=100.0,
                              num_recycles=0, msa_depth=MSA_DEPTH)
        with Scheduler(_BoomExecutor(), BucketPolicy((16,)), cfg,
                       cache=FoldCache(), model_tag="m") as sched:
            tickets = [sched.submit(r) for r in self._dup_requests(3)]
            resps = [t.result(timeout=60) for t in tickets]
        assert all(r.status == "error" for r in resps)
        leader, followers = resps[0], resps[1:]
        assert "boom" in leader.error
        for f in followers:
            assert f.source == "coalesced"
            assert "coalesced onto leader" in f.error
            assert "boom" in f.error

    def test_cancel_propagates_to_followers(self, model_and_params):
        ex = FoldExecutor(*model_and_params)
        cfg = SchedulerConfig(max_batch_size=8, max_wait_ms=60_000.0,
                              num_recycles=0, msa_depth=MSA_DEPTH)
        sched = Scheduler(ex, BucketPolicy((16,)), cfg,
                          cache=FoldCache(), model_tag="m")
        sched.start()
        tickets = [sched.submit(r) for r in self._dup_requests(3)]
        sched.stop(drain=False)
        resps = [t.result(timeout=60) for t in tickets]
        assert [r.status for r in resps] == ["cancelled"] * 3
        assert resps[1].source == "coalesced"
        assert ex.stats()["misses"] == 0       # nothing ever folded

    def test_followers_count_against_queue_limit(self, model_and_params):
        """A duplicate storm on one hot key must hit queue_limit
        backpressure, not grow the in-flight registry unboundedly."""
        ex = FoldExecutor(*model_and_params)
        cfg = SchedulerConfig(max_batch_size=8, max_wait_ms=60_000.0,
                              queue_limit=2, full_policy="reject",
                              num_recycles=0, msa_depth=MSA_DEPTH)
        sched = Scheduler(ex, BucketPolicy((16,)), cfg,
                          cache=FoldCache(), model_tag="m")
        sched.start()
        reqs = self._dup_requests(3)
        t_leader = sched.submit(reqs[0])       # queued (depth 1)
        t_follow = sched.submit(reqs[1])       # parked (waiting 1)
        with pytest.raises(QueueFullError, match="coalesced followers"):
            sched.submit(reqs[2])              # depth+waiting at limit
        sched.stop(drain=False)
        assert t_leader.result(timeout=60).status == "cancelled"
        assert t_follow.result(timeout=60).status == "cancelled"
        assert ex.stats()["misses"] == 0

    def test_block_mode_duplicate_storm_no_deadlock(self,
                                                    model_and_params):
        """full_policy='block' + hot-key duplicates at queue_limit=1:
        the leader must never wait on capacity occupied by its OWN
        parked followers (circular wait). All tickets resolve."""
        ex = FoldExecutor(*model_and_params)
        cfg = SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                              queue_limit=1, full_policy="block",
                              num_recycles=0, msa_depth=MSA_DEPTH)
        reqs = self._dup_requests(4)
        results = []
        lock = threading.Lock()
        with Scheduler(ex, BucketPolicy((16,)), cfg, cache=FoldCache(),
                       model_tag="m") as sched:
            def go(r):
                resp = sched.submit(r).result(timeout=120)
                with lock:
                    results.append(resp)

            threads = [threading.Thread(target=go, args=(r,))
                       for r in reqs]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert len(results) == 4
        assert all(r.ok for r in results), [r.error for r in results]

    def test_corrupt_disk_entry_recomputes_via_scheduler(
            self, model_and_params, tmp_path):
        """Acceptance: a corrupted on-disk entry is a miss — recompute
        succeeds, the entry is quarantined, and no exception escapes
        submit()/result()."""
        d = str(tmp_path / "store")
        cache = FoldCache(disk_dir=d)
        cfg = SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                              num_recycles=0, msa_depth=MSA_DEPTH)
        req = self._dup_requests(1)[0]
        key = fold_key(req.seq, req.msa, msa_depth=MSA_DEPTH,
                       num_recycles=0, model_tag="m")
        path = cache._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(b"\x00garbage\xff" * 64)

        ex = FoldExecutor(*model_and_params)
        with Scheduler(ex, BucketPolicy((16,)), cfg, cache=cache,
                       model_tag="m") as sched:
            resp = sched.submit(req).result(timeout=600)
        assert resp.ok and resp.source == "fold"
        assert np.isfinite(resp.coords).all()
        assert cache.stats.disk_errors == 1
        assert os.path.exists(path + ".quarantined")
        assert cache.get(key) is not None      # repopulated by completion


class TestSchedulerEndToEnd:
    def test_half_duplicate_workload_folds_unique_only(
            self, model_and_params, tmp_path):
        """Acceptance demo: 32 requests, 50% duplicates — only the 16
        unique folds reach the executor; every duplicate resolves from
        the store or by coalescing; serve_stats()/JSONL expose the
        cache section."""
        jsonl = str(tmp_path / "serve.jsonl")
        ex = FoldExecutor(*model_and_params, max_entries=4)
        metrics = ServeMetrics(jsonl)
        cache = FoldCache(disk_dir=str(tmp_path / "store"))
        cfg = SchedulerConfig(max_batch_size=4, max_wait_ms=20.0,
                              num_recycles=0, msa_depth=MSA_DEPTH)
        policy = BucketPolicy((16, 32))
        uniq = synthetic_requests(jax.random.PRNGKey(7), num=16,
                                  lengths=(12, 24), msa_depth=MSA_DEPTH)
        reqs = [FoldRequest(seq=u.seq, msa=u.msa)
                for u in uniq for _ in (0, 1)]      # every request twice
        assert len(reqs) == 32

        tickets = []
        tickets_lock = threading.Lock()
        with Scheduler(ex, policy, cfg, metrics, cache=cache,
                       model_tag="e2e") as sched:
            def submit_slice(i):
                for r in reqs[i::4]:
                    t = sched.submit(r)
                    with tickets_lock:
                        tickets.append(t)

            threads = [threading.Thread(target=submit_slice, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            responses = [t.result(timeout=600) for t in tickets]
            snap = sched.serve_stats()

        assert len(responses) == 32
        for resp in responses:
            assert resp.ok, resp.error
            assert resp.coords is not None and resp.confidence is not None
            assert np.isfinite(resp.coords).all()

        # only unique work hit the accelerator path
        assert snap["served"] <= 16
        by_source = {s: sum(r.source == s for r in responses)
                     for s in ("fold", "cache", "coalesced")}
        assert by_source["fold"] == snap["served"]
        assert by_source["cache"] + by_source["coalesced"] >= 16
        assert ex.stats()["misses"] <= policy.num_buckets   # compile bound

        # serve_stats cache section: counters + store + inflight
        c = snap["cache"]
        assert c["hits"] == by_source["cache"]
        assert c["coalesced"] == by_source["coalesced"]
        assert c["hits"] + c["misses"] == 32
        assert 0.0 <= c["hit_ratio"] <= 1.0
        assert c["store"]["bytes_resident"] > 0
        assert c["store"]["puts"] == snap["served"]
        assert c["inflight"]["inflight_keys"] == 0          # all settled
        metrics.close()

        # JSONL cache section rides along with every batch record
        records = [json.loads(line) for line in open(jsonl)]
        assert records
        for rec in records:
            assert "cache" in rec
            for field in ("hits", "misses", "coalesced", "hit_ratio",
                          "bytes_resident", "evictions"):
                assert field in rec["cache"]

        # identical traffic against the same disk store, fresh process:
        # pure cache hits, executor never touched
        ex2 = FoldExecutor(*model_and_params)
        cache2 = FoldCache(disk_dir=str(tmp_path / "store"))
        with Scheduler(ex2, policy, cfg, cache=cache2,
                       model_tag="e2e") as sched2:
            replay = [sched2.submit(FoldRequest(seq=u.seq, msa=u.msa))
                      .result(timeout=60) for u in uniq]
        assert all(r.ok and r.source == "cache" for r in replay)
        assert ex2.stats()["misses"] == 0


class TestFoldAndWriteCache:
    def test_second_call_skips_fold(self, model_and_params, tmp_path,
                                    monkeypatch):
        model, params = model_and_params
        n, msa_d = 16, MSA_DEPTH
        rng = np.random.default_rng(5)
        seq = jnp.asarray(rng.integers(0, 20, (2, n)), jnp.int32)
        msa = jnp.asarray(rng.integers(0, 20, (2, msa_d, n)), jnp.int32)
        mask = np.ones((2, n), bool)
        mask[1, 12:] = False                   # ragged second element
        kwargs = dict(msa=msa, mask=jnp.asarray(mask),
                      msa_mask=jnp.ones((2, msa_d, n), bool),
                      num_recycles=0)

        calls = []
        real_fold = predict.fold

        def counting_fold(*a, **kw):
            calls.append(1)
            return real_fold(*a, **kw)

        monkeypatch.setattr(predict, "fold", counting_fold)
        cache = FoldCache()
        out = str(tmp_path / "out.pdb")
        paths = predict.fold_and_write(model, params, seq, out,
                                       cache=cache, model_tag="w",
                                       **kwargs)
        assert len(paths) == 2 and all(os.path.exists(p) for p in paths)
        assert len(calls) == 1 and cache.stats.puts == 2

        paths2 = predict.fold_and_write(model, params, seq, out,
                                        cache=cache, model_tag="w",
                                        **kwargs)
        assert len(calls) == 1                 # memoized: no second fold
        assert paths2 == paths
        assert cache.stats.hits == 2
        # identical content on the cached rewrite
        for p in paths:
            assert os.path.getsize(p) > 0

    def test_msa_mask_separates(self, model_and_params, tmp_path):
        """Two calls differing only in msa_mask are different
        computations and must not share a cache entry."""
        model, params = model_and_params
        n, msa_d = 16, MSA_DEPTH
        rng = np.random.default_rng(6)
        seq = jnp.asarray(rng.integers(0, 20, (1, n)), jnp.int32)
        msa = jnp.asarray(rng.integers(0, 20, (1, msa_d, n)), jnp.int32)
        mm1 = np.ones((1, msa_d, n), bool)
        mm2 = mm1.copy()
        mm2[0, 1:] = False                 # mask out deeper rows
        cache = FoldCache()
        out = str(tmp_path / "m.pdb")
        predict.fold_and_write(model, params, seq, out, cache=cache,
                               msa=msa, msa_mask=jnp.asarray(mm1),
                               num_recycles=0)
        predict.fold_and_write(model, params, seq, out, cache=cache,
                               msa=msa, msa_mask=jnp.asarray(mm2),
                               num_recycles=0)
        assert cache.stats.hits == 0 and cache.stats.puts == 2

    def test_offline_entries_cross_hit_the_server(self, model_and_params,
                                                  tmp_path):
        """One shared FoldCache: a fold_and_write run (no extras,
        trivial msa_mask) populates entries the serving scheduler then
        hits for the same content (msa_depth=None config)."""
        model, params = model_and_params
        n, msa_d = 12, MSA_DEPTH
        rng = np.random.default_rng(8)
        seq = rng.integers(0, 20, n).astype(np.int32)
        msa = rng.integers(0, 20, (msa_d, n)).astype(np.int32)
        cache = FoldCache()
        predict.fold_and_write(
            model, params, jnp.asarray(seq[None]),
            str(tmp_path / "x.pdb"), cache=cache, model_tag="shared",
            msa=jnp.asarray(msa[None]), mask=jnp.ones((1, n), bool),
            msa_mask=jnp.ones((1, msa_d, n), bool), num_recycles=0)
        assert cache.stats.puts == 1

        ex = FoldExecutor(*model_and_params)
        cfg = SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                              num_recycles=0)          # msa_depth=None
        with Scheduler(ex, BucketPolicy((16,)), cfg, cache=cache,
                       model_tag="shared") as sched:
            resp = sched.submit(
                FoldRequest(seq=seq, msa=msa)).result(timeout=60)
        assert resp.ok and resp.source == "cache", (resp.source,
                                                    resp.error)
        assert ex.stats()["misses"] == 0               # never folded

    def test_array_extras_disable_caching(self, model_and_params,
                                          tmp_path, monkeypatch):
        """An array-valued extra kwarg (batched per-element
        conditioning) can't be attributed to one element's key — the
        call must fold uncached, never share entries."""
        model, params = model_and_params
        seq = jnp.zeros((1, 8), jnp.int32)
        calls = []

        def stub_fold(model, params, seq, **kw):
            calls.append(1)
            b, n = np.asarray(seq).shape

            class R:
                coords = np.zeros((b, n, 3), np.float32)
                confidence = np.zeros((b, n), np.float32)

            return R()

        monkeypatch.setattr(predict, "fold", stub_fold)
        cache = FoldCache()
        out = str(tmp_path / "e.pdb")
        cond = np.arange(2, dtype=np.float32)
        for _ in range(2):
            predict.fold_and_write(model, params, seq, out, cache=cache,
                                   cond=cond, num_recycles=0)
        assert len(calls) == 2                 # no memoization
        assert cache.stats.puts == 0 and cache.stats.hits == 0
        # scalar extras stay cacheable (and separate keys)
        predict.fold_and_write(model, params, seq, out, cache=cache,
                               cond_scale=2.0, num_recycles=0)
        predict.fold_and_write(model, params, seq, out, cache=cache,
                               cond_scale=2.0, num_recycles=0)
        assert len(calls) == 3 and cache.stats.hits == 1
        predict.fold_and_write(model, params, seq, out, cache=cache,
                               cond_scale=3.0, num_recycles=0)
        assert len(calls) == 4                 # different scalar, new key

    def test_model_tag_separates(self, model_and_params, tmp_path):
        model, params = model_and_params
        seq = jnp.zeros((1, 16), jnp.int32)
        cache = FoldCache()
        out = str(tmp_path / "a.pdb")
        predict.fold_and_write(model, params, seq, out, cache=cache,
                               model_tag="v1", num_recycles=0)
        assert cache.stats.hits == 0
        predict.fold_and_write(model, params, seq, out, cache=cache,
                               model_tag="v2", num_recycles=0)
        assert cache.stats.hits == 0           # different weights tag
        predict.fold_and_write(model, params, seq, out, cache=cache,
                               model_tag="v1", num_recycles=0)
        assert cache.stats.hits == 1
