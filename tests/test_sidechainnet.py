"""Sidechainnet local-corpus adapter (reference train_pre.py:37-47
`scn.load`): pickle-format loading, PDB demo corpus, and a real-data
distogram training run with decreasing loss on the 1H22 crystal fixture.
"""

import json
import os
import pickle

import jax
import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "1h22_head.pdb")


def _fake_scn_pickle(path, n_train=3, lengths=(40, 60, 30)):
    """A miniature pickle in the sidechainnet on-disk format."""
    rng = np.random.default_rng(0)
    aas = "ARNDCQEGHILKMFPSTWYV"

    def protein(L):
        seq = "".join(rng.choice(list(aas)) for _ in range(L))
        # chain-like CA trace with small atom clouds around it
        ca = np.cumsum(rng.normal(0, 1.5, (L, 3)), axis=0)
        crd = (ca[:, None] + rng.normal(0, 0.5, (L, 14, 3))).reshape(-1, 3)
        msk = "".join("+" if rng.random() > 0.1 else "-" for _ in range(L))
        return seq, crd.astype(np.float32), msk

    train = {"seq": [], "crd": [], "msk": [], "ids": []}
    for i, L in enumerate(lengths[:n_train]):
        s, c, m = protein(L)
        train["seq"].append(s)
        train["crd"].append(c)
        train["msk"].append(m)
        train["ids"].append(f"P{i}")
    data = {"train": train, "valid-10": {"seq": [], "crd": []},
            "settings": {"casp_version": 12, "thinning": 30},
            "description": "fake", "date": "2026"}
    with open(path, "wb") as f:
        pickle.dump(data, f)


class TestScnPickle:
    @pytest.mark.quick
    def test_load_and_batch(self, tmp_path):
        from alphafold2_tpu.data.sidechainnet import (SidechainnetDataModule,
                                                      load_scn_pickle)

        p = str(tmp_path / "scn.pkl")
        _fake_scn_pickle(p)
        splits = load_scn_pickle(p)
        assert "train" in splits and "settings" not in splits

        dm = SidechainnetDataModule(p, crop_len=32, batch_size=2)
        batch = next(dm.train_batches())
        assert batch["seq"].shape == (2, 32)
        assert batch["coords14"].shape == (2, 32, 14, 3)
        assert batch["dist"].shape == (2, 32, 32)
        assert batch["msa"].shape[0:1] == (2,)
        # supervised targets exist and unresolved ('-') residues are
        # excluded via the zero-coord convention
        assert (batch["dist"] >= 0).any()

    @pytest.mark.quick
    def test_threshold_length_filter(self, tmp_path):
        from alphafold2_tpu.data.sidechainnet import SidechainnetDataModule

        p = str(tmp_path / "scn.pkl")
        _fake_scn_pickle(p)
        dm = SidechainnetDataModule(p, crop_len=16, max_len=45)
        # lengths (40, 60, 30): the 60-residue protein is filtered, the
        # reference's THRESHOLD_LENGTH semantics (train_pre.py:19,45)
        assert len(dm.train_ds) == 2

    @pytest.mark.quick
    def test_bad_pickle_rejected(self, tmp_path):
        from alphafold2_tpu.data.sidechainnet import load_scn_pickle

        p = str(tmp_path / "bad.pkl")
        with open(p, "wb") as f:
            pickle.dump({"not": "scn"}, f)
        with pytest.raises(ValueError):
            load_scn_pickle(p)


class TestPdbCorpus:
    @pytest.mark.quick
    def test_corpus_from_fixture(self):
        from alphafold2_tpu.data.sidechainnet import (SidechainnetDataModule,
                                                      corpus_from_pdb)

        corpus = corpus_from_pdb([FIXTURE])
        assert len(corpus["seq"]) == 1
        L = len(corpus["seq"][0])
        assert corpus["crd"][0].shape == (L * 14, 3)
        assert set(corpus["msk"][0]) <= {"+", "-"}

        dm = SidechainnetDataModule(corpus, crop_len=32, batch_size=1)
        batch = next(dm.train_batches())
        assert (batch["dist"] >= 0).any()
        assert bool(np.isfinite(batch["coords14"]).all())


class TestRealDataTraining:
    def test_distogram_loss_descends_on_crystal_structure(self, tmp_path):
        """The round-2 VERDICT demo: a short train_distogram.py run on
        real structure data (1H22 residues 4-75) with decreasing loss."""
        from scripts.train_distogram import main

        cfg = {"model": {"dim": 32, "depth": 1, "heads": 2, "dim_head": 16,
                         "bfloat16": False},
               "data": {"crop_len": 48, "msa_depth": 1, "batch_size": 1},
               "train": {"num_steps": 25, "log_every": 5,
                         "learning_rate": 1e-3, "grad_accum_every": 1}}
        cfg_path = str(tmp_path / "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)

        history = main(["--config", cfg_path, "--pdb", FIXTURE])
        losses = [h["loss"] for h in history]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9, losses
