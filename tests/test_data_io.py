"""Dataset / PDB-IO / relax tests: trrosetta-style loader over synthetic
on-disk samples, PDB write->parse round trip, and the gradient relaxer."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from alphafold2_tpu import relax
from alphafold2_tpu.core import nerf
from alphafold2_tpu.data import featurize, native, pdb_io
from alphafold2_tpu.data.trrosetta import TrRosettaDataModule, TrRosettaDataset

pytestmark = pytest.mark.quick


def write_sample(root, sample_id, length, rng):
    seq = "".join(rng.choice(list("ARNDCQEGHILKMFPSTWYV"), length))
    rows = [seq]
    for _ in range(3):
        row = list(seq)
        for pos in rng.integers(0, length, 3):
            row[pos] = "-"
        rows.append("".join(row))
    a3m = "\n".join(f">r{i}\n{r}" for i, r in enumerate(rows)) + "\n"
    (root / f"{sample_id}.a3m").write_text(a3m)

    # idealized-geometry structure via the NeRF builder -> PDB text
    tokens = featurize.tokenize(seq)
    backbone = np.cumsum(rng.normal(size=(1, length, 3, 3)) * 1.3, axis=1)
    coords14 = nerf.sidechain_container(jnp.asarray(backbone),
                                        jnp.asarray(tokens)[None])
    from alphafold2_tpu.data.scn import scn_cloud_mask
    cloud = scn_cloud_mask(jnp.asarray(tokens)[None])
    pdb_io.coords2pdb(tokens, np.asarray(coords14[0]),
                      np.asarray(cloud[0]).astype(bool),
                      name=str(root / f"{sample_id}.pdb"))
    return seq


class TestTrRosetta:
    def test_dataset_and_module(self, tmp_path):
        rng = np.random.default_rng(0)
        for i in range(3):
            write_sample(tmp_path, f"s{i}", 24 + 4 * i, rng)

        ds = TrRosettaDataset(str(tmp_path))
        assert len(ds) == 3
        sample = ds[0]
        assert sample["msa"].shape[0] == 4
        assert "coords" in sample
        assert sample["coords"].shape[1:] == (14, 3)

        # featurized cache written (config-digest naming) and reused
        cache_path = ds._cache_path("s0")
        assert os.path.exists(cache_path)
        again = ds[0]
        assert np.array_equal(again["seq"], sample["seq"])
        # a different featurize config names a different cache file:
        # stale features can never be served across configs
        assert TrRosettaDataset(
            str(tmp_path), max_msa_rows=7)._cache_path("s0") != cache_path

        dm = TrRosettaDataModule(str(tmp_path), crop_len=16, batch_size=2,
                                 max_msa_rows=3)
        batch = next(dm.train_batches())
        assert batch["seq"].shape == (2, 16)
        assert batch["msa"].shape == (2, 3, 16)
        assert batch["dist"].shape == (2, 16, 16)
        assert batch["coords"].shape == (2, 16, 3)


class TestPdbIO:
    def test_write_parse_roundtrip(self, tmp_path):
        rng = np.random.default_rng(1)
        length = 10
        seq_str = "".join(rng.choice(list("ARNDCQEGHILKMFPSTWYV"), length))
        tokens = featurize.tokenize(seq_str)
        backbone = np.cumsum(rng.normal(size=(1, length, 3, 3)) * 1.3, 1)
        coords14 = np.asarray(nerf.sidechain_container(
            jnp.asarray(backbone), jnp.asarray(tokens)[None]))[0]
        from alphafold2_tpu.data.scn import scn_cloud_mask
        cloud = np.asarray(scn_cloud_mask(jnp.asarray(tokens)[None]))[0] > 0

        path = pdb_io.coords2pdb(tokens, coords14, cloud,
                                 name=str(tmp_path / "x.pdb"))
        with open(path) as f:
            seq2, coords2, mask2 = native.parse_pdb(f.read())
        assert np.array_equal(seq2, tokens)
        assert np.array_equal(mask2, cloud)
        # PDB format stores 3 decimals
        assert np.allclose(coords2[mask2], coords14[cloud], atol=2e-3)

    def test_clean_pdb(self, tmp_path):
        text = ("ATOM      1  N   ALA A   1      1.0     2.0     3.0"
                "  1.00  0.00           N\n"
                "ATOM      2  N   GLY B   1      1.0     2.0     3.0"
                "  1.00  0.00           N\nEND\n")
        src = tmp_path / "in.pdb"
        src.write_text(text)
        out = pdb_io.clean_pdb(str(src), str(tmp_path / "out.pdb"))
        cleaned = open(out).read()
        assert " A " in cleaned or "ALA" in cleaned
        assert "GLY" not in cleaned


class TestRelax:
    def test_gradient_relax_reduces_energy(self):
        rng = np.random.default_rng(2)
        length = 6
        seq = jnp.asarray(featurize.tokenize(
            "".join(rng.choice(list("ARNDCQEGHILKMFPSTWYV"), length))))[None]
        backbone = jnp.asarray(
            np.cumsum(rng.normal(size=(1, length, 3, 3)) * 2.0, 1))
        coords14 = nerf.sidechain_container(backbone, seq)
        # perturb so restraints are violated
        noisy = coords14 + jax.random.normal(
            jax.random.PRNGKey(0), coords14.shape) * 0.4
        result = relax.gradient_relax(noisy, seq, steps=30)
        assert bool(jnp.isfinite(result.coords).all())
        assert float(result.energy_history[-1]) < \
            float(result.energy_history[0])
