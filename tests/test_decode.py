"""Legacy decode-path tests (distogram -> MDS -> mirror fix -> sidechain
build-out) mirroring the reference's tests/test_utils.py contracts, plus
recovery/property tests it lacks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import constants
from alphafold2_tpu.core import geometry as geo
from alphafold2_tpu.core import mds, nerf
from alphafold2_tpu.data import featurize, graph, scn

pytestmark = pytest.mark.quick


class TestMDS:
    def make_cloud(self, key, b=1, n=24):
        return jax.random.normal(key, (b, n, 3)) * 4

    def test_eigen_init_recovers_geometry(self):
        pts = self.make_cloud(jax.random.PRNGKey(0))
        d = geo.cdist(pts, pts)
        rec = mds.eigen_init(d)
        assert float(geo.kabsch_rmsd(rec, pts - pts.mean(1, keepdims=True)
                                     ).max()) < 0.5 or True
        # distances are chirality/rotation invariant — compare distance mats
        d_rec = geo.cdist(rec, rec)
        assert float(jnp.abs(d_rec - d).mean()) < 0.5

    def test_mds_iterations_reduce_stress(self):
        pts = self.make_cloud(jax.random.PRNGKey(1))
        d = geo.cdist(pts, pts)
        noisy = d + jax.random.normal(jax.random.PRNGKey(2), d.shape) * 0.3
        noisy = 0.5 * (noisy + noisy.swapaxes(-1, -2))
        res = mds.mds(noisy, iters=10)
        d_rec = geo.cdist(res.coords, res.coords)
        assert float(jnp.abs(d_rec - d).mean()) < 1.0
        assert res.stress_history.shape == (10, 1)

    def test_mds_weighted(self):
        pts = self.make_cloud(jax.random.PRNGKey(3))
        d = geo.cdist(pts, pts)
        w = jnp.ones_like(d)
        res = mds.mds(d, weights=w, iters=5)
        assert bool(jnp.isfinite(res.coords).all())

    def test_mirror_fix_flips_wrong_chirality(self):
        # build a cloud, compute its phi fraction; mirrored input must come
        # back with the same chirality statistic as the original
        key = jax.random.PRNGKey(4)
        l = 10
        pts = jax.random.normal(key, (1, l * 3, 3)) * 3
        n_idx, ca_idx, c_idx = (jnp.arange(l) * 3, jnp.arange(l) * 3 + 1,
                                jnp.arange(l) * 3 + 2)
        frac = geo.fraction_negative_phis(pts[:, n_idx], pts[:, ca_idx],
                                          pts[:, c_idx])
        fixed = mds.mirror_fix(pts, n_idx, ca_idx, c_idx)
        frac_fixed = geo.fraction_negative_phis(
            fixed[:, n_idx], fixed[:, ca_idx], fixed[:, c_idx])
        assert float(frac_fixed[0]) >= 0.5 or np.isclose(
            float(frac[0]), float(frac_fixed[0]))

    def test_mdscaling_end_to_end(self):
        # distogram-shaped decode: distances + weights -> 3D
        pts = self.make_cloud(jax.random.PRNGKey(5), n=30)
        d = geo.cdist(pts, pts)
        l = 10
        n_idx, ca_idx, c_idx = (jnp.arange(l) * 3, jnp.arange(l) * 3 + 1,
                                jnp.arange(l) * 3 + 2)
        res = mds.mdscaling(d, iters=8, n_idx=n_idx, ca_idx=ca_idx,
                            c_idx=c_idx)
        assert res.coords.shape == (1, 30, 3)
        d_rec = geo.cdist(res.coords, res.coords)
        assert float(jnp.abs(d_rec - d).mean()) < 0.5


class TestNerf:
    def test_nerf_place_geometry(self):
        a = jnp.array([0.0, 0, 0])
        b = jnp.array([1.5, 0, 0])
        c = jnp.array([1.5, 1.5, 0])
        d = nerf.nerf_place(a, b, c, 1.5, jnp.deg2rad(109.5),
                            jnp.deg2rad(180.0))
        # bond length respected
        assert np.isclose(float(jnp.linalg.norm(d - c)), 1.5, atol=1e-5)
        # bond angle respected
        v1 = b - c
        v2 = d - c
        cosang = jnp.dot(v1, v2) / (jnp.linalg.norm(v1) * jnp.linalg.norm(v2))
        assert np.isclose(float(jnp.arccos(cosang)), np.deg2rad(109.5),
                          atol=1e-4)
        # torsion respected
        tor = geo.dihedral(a, b, c, d)
        assert np.isclose(abs(float(tor)), np.pi, atol=1e-4)

    def test_sidechain_container_shapes(self):
        # reference test_utils.py:63-68 contract: (2, L, 14, 3)
        b, l = 2, 17
        backbone = jnp.cumsum(
            jax.random.normal(jax.random.PRNGKey(0), (b, l, 3, 3)), axis=1)
        seq = jax.random.randint(jax.random.PRNGKey(1), (b, l), 0, 20)
        out = nerf.sidechain_container(backbone, seq)
        assert out.shape == (b, l, 14, 3)
        assert bool(jnp.isfinite(out).all())
        # backbone slots preserved
        assert np.allclose(out[:, :, :3], backbone)

    def test_sidechain_bond_lengths_sane(self):
        b, l = 1, 8
        backbone = jnp.cumsum(
            jax.random.normal(jax.random.PRNGKey(2), (b, l, 3, 3)) * 1.2,
            axis=1)
        seq = jnp.full((b, l), featurize.AA_INDEX["L"])  # leucine
        out = nerf.sidechain_container(backbone, seq)
        # CB-CA distance ~1.52
        d = jnp.linalg.norm(out[:, :, 4] - out[:, :, 1], axis=-1)
        assert np.allclose(d, 1.52, atol=0.05)

    def test_sidechain_differentiable(self):
        b, l = 1, 6
        backbone = jnp.cumsum(
            jax.random.normal(jax.random.PRNGKey(3), (b, l, 3, 3)), axis=1)
        seq = jnp.full((b, l), featurize.AA_INDEX["K"])

        def f(bb):
            return (nerf.sidechain_container(bb, seq) ** 2).sum()

        g = jax.grad(f)(backbone)
        assert bool(jnp.isfinite(g).all())
        assert float(jnp.abs(g).sum()) > 0

    def test_ca_only_input(self):
        b, l = 1, 9
        ca = jnp.cumsum(jax.random.normal(jax.random.PRNGKey(4),
                                          (b, l, 1, 3)) * 2, axis=1)
        seq = jnp.full((b, l), featurize.AA_INDEX["A"])
        out = nerf.sidechain_container(ca, seq)
        assert out.shape == (b, l, 14, 3)
        assert bool(jnp.isfinite(out).all())


class TestScn:
    def test_cloud_mask_from_seq(self):
        seq = jnp.asarray([[featurize.AA_INDEX["G"], featurize.AA_INDEX["W"],
                            featurize.AA_INDEX["_"]]])
        m = scn.scn_cloud_mask(seq)
        assert m.shape == (1, 3, 14)
        assert m[0, 0].sum() == 4    # Gly: backbone only
        assert m[0, 1].sum() == 14   # Trp: all slots
        assert m[0, 2].sum() == 0    # padding

    def test_cloud_mask_from_coords(self):
        coords = jnp.zeros((1, 2, 14, 3)).at[0, 0, :5].set(1.0)
        m = scn.scn_cloud_mask(jnp.zeros((1, 2), jnp.int32), coords=coords)
        assert m[0, 0].sum() == 5 and m[0, 1].sum() == 0

    def test_backbone_masks(self):
        seq = jnp.zeros((2, 5), jnp.int32)
        n_m, ca_m, c_m = scn.scn_backbone_mask(seq)
        assert n_m.shape == (2, 70)
        assert int(n_m.sum()) == 10 and int(ca_m.sum()) == 10
        n_i, ca_i, c_i = scn.backbone_indices(5)
        assert np.array_equal(np.asarray(n_i), np.arange(5) * 14)
        assert np.array_equal(np.asarray(ca_i), np.arange(5) * 14 + 1)

    def test_atom_embedd(self):
        seq = jnp.asarray([[featurize.AA_INDEX["A"]]])
        e = scn.scn_atom_embedd(seq)
        assert e.shape == (1, 1, 14)
        assert int(e[0, 0, 0]) == constants.ATOM_IDS["N"]
        assert int(e[0, 0, 4]) == constants.ATOM_IDS["CB"]


class TestGraph:
    def test_covalent_bond_adjacency(self):
        seq = jnp.asarray([[featurize.AA_INDEX["A"],
                            featurize.AA_INDEX["G"]]])
        adj = graph.prot_covalent_bond(seq)
        assert adj.shape == (1, 28, 28)
        # Ala has 4 bonds *2 (sym) + Gly 3*2 + peptide 2 = 16
        assert int(adj.sum()) == 2 * 4 + 2 * 3 + 2
        # peptide bond: C (slot 2) of res 0 to N (slot 14) of res 1
        assert adj[0, 2, 14] == 1 and adj[0, 14, 2] == 1

    def test_neighbor_table_matches_dense_adjacency(self):
        """covalent_neighbor_table is the O(N*K) form of
        prot_covalent_bond: same edge set on random sequences."""
        import numpy as np

        rng = np.random.default_rng(0)
        seq = jnp.asarray(rng.integers(0, 21, size=(2, 7)))
        adj = np.asarray(graph.prot_covalent_bond(seq))
        idx, msk = graph.covalent_neighbor_table(seq)
        idx, msk = np.asarray(idx), np.asarray(msk)
        n = adj.shape[1]
        for b in range(adj.shape[0]):
            dense_edges = {(i, j) for i in range(n) for j in range(n)
                           if adj[b, i, j] > 0}
            table_edges = {(i, int(idx[b, i, s]))
                           for i in range(n)
                           for s in range(idx.shape[-1])
                           if msk[b, i, s] > 0}
            assert table_edges == dense_edges

    def test_nth_degree(self):
        seq = jnp.asarray([[featurize.AA_INDEX["A"]]])
        adj = graph.prot_covalent_bond(seq, include_peptide_bonds=False)
        attr, hops = graph.nth_deg_adjacency(adj, n=2)
        # N-CA-C: N to C is 2 hops
        assert int(hops[0, 0, 2]) == 2
        assert int(hops[0, 0, 1]) == 1

    def test_mat_input_to_masked(self):
        x = jnp.ones((2, 4, 8))
        mask = jnp.ones((2, 4), bool).at[1, 2:].set(False)
        nodes, node_mask, edges, edge_mask = graph.mat_input_to_masked(
            x, mask, edges_mat=jnp.ones((2, 4, 4)))
        assert nodes.shape == (8, 8)
        assert int(node_mask.sum()) == 6
        assert edge_mask.shape == (2, 4, 4)
        assert not bool(edge_mask[1, 3, 3])


class TestFeaturize:
    def test_tokenize_roundtrip(self):
        s = "ARNDCQEGHILKMFPSTWYV"
        t = featurize.tokenize(s)
        assert featurize.detokenize(t) == s
        assert featurize.tokenize("X-z")[0] == featurize.AA_INDEX["_"]

    def test_subsample_keeps_query(self):
        msa = np.arange(50).reshape(10, 5).astype(np.int32)
        sub = featurize.subsample_msa(msa, 4,
                                      np.random.default_rng(0))
        assert sub.shape == (4, 5)
        assert np.array_equal(sub[0], msa[0])

    def test_distance_targets_cb_virtual(self):
        l = 6
        rng = np.random.default_rng(1)
        coords14 = rng.normal(size=(l, 14, 3)).astype(np.float32)
        coords14 = np.cumsum(coords14, axis=0)
        seq = np.full(l, featurize.AA_INDEX["G"], np.int32)  # all Gly
        mask = np.ones(l, bool)
        d = featurize.distance_map_targets(coords14, seq, mask)
        assert d.shape == (l, l)
        assert (d >= 0).all() and (d < 37).all()

    def test_collate_fixed_shapes(self):
        rng = np.random.default_rng(2)
        samples = []
        for length in (30, 50):
            samples.append({
                "seq": rng.integers(0, 20, length).astype(np.int32),
                "msa": rng.integers(0, 20, (8, length)).astype(np.int32),
                "coords": np.cumsum(
                    rng.normal(size=(length, 14, 3)), 0).astype(np.float32),
            })
        batch = featurize.collate(samples, crop_len=40, max_msa_rows=5,
                                  rng=rng)
        assert batch["seq"].shape == (2, 40)
        assert batch["msa"].shape == (2, 5, 40)
        assert batch["coords"].shape == (2, 40, 3)
        assert batch["dist"].shape == (2, 40, 40)
        # sample 0 is shorter than the crop: padding masked out
        assert not batch["mask"][0, 35:].any()
        assert batch["mask"][1].all()
        assert (batch["dist"][0, 35:] == constants.IGNORE_INDEX).all()


class TestNerfAccuracy:
    """NeRF idealized-geometry accuracy against a real crystal structure
    (round-1 VERDICT Weak #7: the ~0.03 A claim was never measured).

    Fixture: residues 4-75 of PDB entry 1H22 chain A (public PDB data).
    The build-graph edges of `sidechain_container` are real covalent
    bonds, so for every present atom pair the built bond length (the
    idealized table value) must match the crystal bond length to
    sub-0.1 A per bond.
    """

    @classmethod
    def _load(cls):
        import os
        from alphafold2_tpu.data import native
        path = os.path.join(os.path.dirname(__file__), "data",
                            "1h22_head.pdb")
        with open(path) as f:
            return native.parse_pdb(f.read())

    def test_per_bond_error_vs_crystal(self):
        seq, coords, mask = self._load()
        seq = np.asarray(seq, np.int32)
        coords = np.asarray(coords)
        mask = np.asarray(mask)
        l = seq.shape[0]
        assert l >= 60  # the fixture really parsed

        built = np.asarray(nerf.sidechain_container(
            jnp.asarray(coords[None, :, :3]), jnp.asarray(seq[None])))[0]

        parent = np.asarray(nerf._PARENT)[seq]   # (l, 14)
        build = np.asarray(nerf._BUILD)[seq]     # (l, 14)
        errs = []
        for i in range(l):
            for slot in range(4, constants.NUM_COORDS_PER_RES):
                p = parent[i, slot]
                if build[i, slot] == 0 or not (mask[i, slot] and mask[i, p]):
                    continue
                real = np.linalg.norm(coords[i, slot] - coords[i, p])
                ours = np.linalg.norm(built[i, slot] - built[i, p])
                errs.append(abs(real - ours))
        errs = np.asarray(errs)
        assert errs.size > 200  # enough bonds to be meaningful
        assert errs.mean() < 0.03, f"mean per-bond error {errs.mean():.3f} A"
        # sub-0.1 A per bond, tolerating the fixture's own distorted
        # outliers (1H22 models two MET SD-CE bonds at 1.60/1.96 A where
        # thioether chemistry says ~1.79 — the error there is the
        # crystal's, not the build's)
        frac_ok = float((errs < 0.1).mean())
        assert frac_ok > 0.97, f"only {frac_ok:.1%} of bonds under 0.1 A"

    def test_backbone_o_placement(self):
        """place_o's sp2 carbonyl geometry vs the crystal: C=O length and
        the O position itself (fully determined by the backbone frame up
        to the psi-dependent anti torsion; compare bond length only)."""
        seq, coords, mask = self._load()
        coords = np.asarray(coords)
        mask = np.asarray(mask)
        ok = mask[:, :4].all(axis=1)
        n_at, ca, c_at, o_real = (coords[ok, 0], coords[ok, 1],
                                  coords[ok, 2], coords[ok, 3])
        o_built = np.asarray(nerf.place_o(jnp.asarray(n_at),
                                          jnp.asarray(ca),
                                          jnp.asarray(c_at)))
        real_len = np.linalg.norm(o_real - c_at, axis=-1)
        built_len = np.linalg.norm(o_built - c_at, axis=-1)
        err = np.abs(real_len - built_len)
        assert err.max() < 0.1 and err.mean() < 0.03
