"""Aux-subsystem tests: config tree round-trip + build, metrics logger,
step timer, and the training entry scripts end-to-end (tiny)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from alphafold2_tpu.config import Experiment, ModelConfig
from alphafold2_tpu.utils import MetricsLogger, StepTimer

pytestmark = pytest.mark.quick

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestConfig:
    def test_roundtrip(self):
        exp = Experiment()
        exp.model.dim = 64
        exp.model.reversible = True
        exp.mesh.i = 2
        text = exp.to_json()
        back = Experiment.from_json(text)
        assert back.model.dim == 64
        assert back.model.reversible
        assert back.mesh.i == 2

    def test_build(self):
        exp = Experiment()
        exp.model.dim, exp.model.depth = 32, 1
        exp.model.bfloat16 = False
        model, tx, mesh = exp.build()
        assert model.dim == 32
        assert mesh is None  # 1x1x1
        assert tx is not None

    def test_model_config_matches_model_fields(self):
        import jax
        model = ModelConfig(dim=32, depth=1, bfloat16=False).build()
        seq = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 21)
        params = model.init(jax.random.PRNGKey(1), seq)
        ret = model.apply(params, seq)
        assert ret.distance.shape == (1, 8, 8, 37)


class TestLoggerTimer:
    def test_metrics_logger_jsonl(self, tmp_path):
        path = tmp_path / "m.jsonl"
        with MetricsLogger(str(path), stdout=False) as log:
            log.log(step=0, loss=1.5)
            log.log(step=1, loss=1.25, extra=2)
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 2
        rec = json.loads(lines[1])
        assert rec["step"] == 1 and np.isclose(rec["loss"], 1.25)

    def test_step_timer(self):
        t = StepTimer()
        for _ in range(3):
            with t.measure():
                pass
        s = t.summary()
        assert s["count"] == 3
        assert s["mean_s"] >= 0


@pytest.mark.parametrize("script,extra", [
    ("scripts/train_distogram.py", []),
    ("scripts/train_end2end.py", ["--structure-module", "egnn"]),
])
def test_training_scripts_run(tmp_path, script, extra):
    """The reference's train scripts are stale/broken (SURVEY.md §2.6);
    ours must actually run: 3 tiny steps on synthetic data."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    cfg = {
        "model": {"dim": 32, "depth": 1, "heads": 2, "dim_head": 16,
                  "bfloat16": False},
        "data": {"crop_len": 12, "msa_depth": 2},
        "train": {"num_steps": 3, "log_every": 1,
                  "grad_accum_every": 1},
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    log_path = tmp_path / "metrics.jsonl"
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, script), "--config",
         str(cfg_path), "--log", str(log_path)] + extra,
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    assert result.returncode == 0, result.stderr[-2000:]
    lines = log_path.read_text().strip().splitlines()
    assert len(lines) == 3
    assert "loss" in json.loads(lines[0])
