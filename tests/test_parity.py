"""Numerical parity vs the reference implementation: copy the reference's
torch module weights into the flax modules and require matching outputs.

This is value-level parity evidence the reference's own test suite never
had (SURVEY.md §4: "crash tests, not value tests"). Component-level on
purpose: the one documented semantic deviation (OuterMean's masked-mean
fix, primitives.py docstring) is excluded by testing OuterMean maskless.

Requires /root/reference and torch (CPU); skipped otherwise.
"""

import os
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

REFERENCE = "/root/reference"
TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")

if not os.path.isdir(REFERENCE):  # pragma: no cover
    pytest.skip("reference not mounted", allow_module_level=True)

torch = pytest.importorskip("torch")
sys.path.insert(0, TOOLS)
sys.path.insert(0, REFERENCE)
import _reference_stubs  # noqa: F401,E402  (fills missing native deps)

from alphafold2_pytorch import alphafold2 as ref  # noqa: E402

from alphafold2_tpu.model import primitives as mine  # noqa: E402

torch.manual_seed(0)


def t2j(t):
    return jnp.asarray(t.detach().cpu().numpy())


def linear(params_leaf, torch_linear):
    """Fill a flax Dense param dict from a torch Linear."""
    out = {"kernel": t2j(torch_linear.weight).T}
    if torch_linear.bias is not None:
        out["bias"] = t2j(torch_linear.bias)
    return out


def layernorm(torch_ln):
    return {"LayerNorm_0": {"scale": t2j(torch_ln.weight),
                            "bias": t2j(torch_ln.bias)}}


def attention_params(ta: "ref.Attention"):
    return {
        "to_q": linear(None, ta.to_q),
        "to_kv": linear(None, ta.to_kv),
        "to_out": linear(None, ta.to_out),
        "gating": linear(None, ta.gating),
    }


def rand_t(*shape):
    return torch.randn(*shape)


class TestAttentionParity:
    def test_basic(self):
        dim, heads, dh, n = 32, 4, 8, 10
        ta = ref.Attention(dim=dim, heads=heads, dim_head=dh).eval()
        ja = mine.Attention(dim=dim, heads=heads, dim_head=dh)
        x = rand_t(2, n, dim)
        with torch.no_grad():
            want = ta(x)
        params = {"params": attention_params(ta)}
        got = ja.apply(params, t2j(x))
        assert np.allclose(np.asarray(got), want.numpy(), atol=1e-5)

    def test_with_bias_and_mask(self):
        dim, heads, dh, n = 32, 4, 8, 12
        ta = ref.Attention(dim=dim, heads=heads, dim_head=dh).eval()
        ja = mine.Attention(dim=dim, heads=heads, dim_head=dh)
        x = rand_t(2, n, dim)
        bias = rand_t(2, heads, n, n)
        mask = torch.ones(2, n).bool()
        mask[:, -3:] = False
        with torch.no_grad():
            want = ta(x, mask=mask, attn_bias=bias)
        got = ja.apply({"params": attention_params(ta)}, t2j(x),
                       mask=t2j(mask), attn_bias=t2j(bias))
        assert np.allclose(np.asarray(got)[:, :-3], want.numpy()[:, :-3],
                           atol=1e-5)

    def test_tie_dim_global_query(self):
        dim, heads, dh, n, r = 32, 2, 8, 6, 3
        ta = ref.Attention(dim=dim, heads=heads, dim_head=dh).eval()
        ja = mine.Attention(dim=dim, heads=heads, dim_head=dh)
        x = rand_t(2 * r, n, dim)
        with torch.no_grad():
            want = ta(x, tie_dim=r)
        got = ja.apply({"params": attention_params(ta)}, t2j(x), tie_dim=r)
        assert np.allclose(np.asarray(got), want.numpy(), atol=1e-5)


class TestAxialParity:
    @pytest.mark.parametrize("row_attn,col_attn", [(True, False),
                                                   (False, True)])
    def test_axial(self, row_attn, col_attn):
        dim, heads, dh = 32, 2, 8
        ta = ref.AxialAttention(dim=dim, heads=heads, dim_head=dh,
                                row_attn=row_attn, col_attn=col_attn,
                                accept_edges=True).eval()
        ja = mine.AxialAttention(dim=dim, heads=heads, dim_head=dh,
                                 row_attn=row_attn, col_attn=col_attn,
                                 accept_edges=True)
        x = rand_t(1, 7, 7, dim)
        edges = rand_t(1, 7, 7, dim)
        with torch.no_grad():
            want = ta(x, edges=edges)
        params = {"params": {
            "LayerNorm_0": layernorm(ta.norm),
            "attn": attention_params(ta.attn),
            "edges_to_attn_bias": linear(None, ta.edges_to_attn_bias[0]),
        }}
        got = ja.apply(params, t2j(x), edges=t2j(edges))
        assert np.allclose(np.asarray(got), want.numpy(), atol=1e-5)


class TestTriangleParity:
    @pytest.mark.parametrize("mix", ["outgoing", "ingoing"])
    def test_triangle_multiplicative(self, mix):
        dim, n = 32, 9
        tm = ref.TriangleMultiplicativeModule(dim=dim, mix=mix).eval()
        jm = mine.TriangleMultiplicativeModule(dim=dim, mix=mix)
        x = rand_t(1, n, n, dim)
        mask = torch.ones(1, n, n).bool()
        with torch.no_grad():
            want = tm(x, mask=mask)
        params = {"params": {
            "LayerNorm_0": layernorm(tm.norm),
            "left_proj": linear(None, tm.left_proj),
            "right_proj": linear(None, tm.right_proj),
            "left_gate": linear(None, tm.left_gate),
            "right_gate": linear(None, tm.right_gate),
            "out_gate": linear(None, tm.out_gate),
            "LayerNorm_1": layernorm(tm.to_out_norm),
            "to_out": linear(None, tm.to_out),
        }}
        got = jm.apply(params, t2j(x), mask=t2j(mask))
        assert np.allclose(np.asarray(got), want.numpy(), atol=1e-4)


class TestFeedForwardParity:
    def test_geglu_ff(self):
        dim = 32
        tf = ref.FeedForward(dim=dim).eval()
        jf = mine.FeedForward(dim=dim)
        x = rand_t(2, 5, dim)
        with torch.no_grad():
            want = tf(x)
        params = {"params": {
            "LayerNorm_0": layernorm(tf.norm),
            "Dense_0": linear(None, tf.net[0]),
            "Dense_1": linear(None, tf.net[3]),
        }}
        got = jf.apply(params, t2j(x))
        assert np.allclose(np.asarray(got), want.numpy(), atol=1e-5)


class TestWholeModelParity:
    """Full-model weight porting (tools/port_weights.py; VERDICT round-1
    item #5): a reference Alphafold2's weights run here and produce the
    same trunk outputs. The flax model runs with
    `outer_mean_reference_scale=True` because the reference synthesizes an
    all-ones msa_mask (alphafold2.py:703), putting its OuterMean in the
    double-dividing masked branch (alphafold2.py:347) on every forward."""

    CFG = dict(dim=32, depth=2, heads=2, dim_head=16, max_seq_len=64,
               extra_msa_evoformer_layers=1, predict_angles=True)

    def _models(self):
        from alphafold2_tpu import Alphafold2
        from port_weights import port_alphafold2

        tmodel = ref.Alphafold2(**self.CFG).eval()
        model = Alphafold2(**self.CFG, outer_mean_reference_scale=True)
        seq = jnp.zeros((1, 8), dtype=jnp.int32)
        template = model.init(jax.random.PRNGKey(0), seq)
        params, unported = port_alphafold2(tmodel, template)
        # everything except the framework-only projection banks and the
        # (non-portable, external-package) IPA internals must be ported
        for k in unported:
            assert k.startswith(("seq_embed_project", "msa_embed_project",
                                 "structure_module")), k
        return tmodel, model, params

    def test_distogram_and_angles_match(self):
        tmodel, model, params = self._models()
        n, m = 16, 3
        seq_t = torch.randint(0, 21, (1, n))
        msa_t = torch.randint(0, 21, (1, m, n))
        with torch.no_grad():
            want = tmodel(seq=seq_t, msa=msa_t)
        got = model.apply(params, t2j(seq_t).astype(jnp.int32),
                          msa=t2j(msa_t).astype(jnp.int32))
        assert np.allclose(np.asarray(got.distance),
                           want.distance.numpy(), atol=2e-4), \
            float(np.abs(np.asarray(got.distance)
                         - want.distance.numpy()).max())
        # the reference assigns ad-hoc *_logits attributes and leaves the
        # declared dataclass fields None (alphafold2.py:32-35 vs :816-836)
        assert np.allclose(np.asarray(got.theta),
                           want.theta_logits.numpy(), atol=2e-4)
        assert np.allclose(np.asarray(got.phi),
                           want.phi_logits.numpy(), atol=2e-4)
        assert np.allclose(np.asarray(got.omega),
                           want.omega_logits.numpy(), atol=2e-4)

    def test_recycling_embeds_match(self):
        tmodel, model, params = self._models()
        n, m = 12, 3
        seq_t = torch.randint(0, 21, (1, n))
        msa_t = torch.randint(0, 21, (1, m, n))
        rec_msa = torch.randn(1, n, 32)
        rec_pair = torch.randn(1, n, n, 32)
        rec_coords = torch.randn(1, n, 3) * 5

        t_rec = ref.Recyclables(rec_coords, rec_msa, rec_pair)
        with torch.no_grad():
            want = tmodel(seq=seq_t, msa=msa_t, recyclables=t_rec)

        from alphafold2_tpu.model.alphafold2 import Recyclables
        j_rec = Recyclables(coords=t2j(rec_coords),
                            single_msa_repr_row=t2j(rec_msa),
                            pairwise_repr=t2j(rec_pair))
        got = model.apply(params, t2j(seq_t).astype(jnp.int32),
                          msa=t2j(msa_t).astype(jnp.int32),
                          recyclables=j_rec)
        assert np.allclose(np.asarray(got.distance),
                           want.distance.numpy(), atol=2e-4)


class TestOuterMeanParity:
    def test_maskless(self):
        # maskless only: the reference's masked branch double-divides
        # (alphafold2.py:347) — our fix is the documented deviation
        dim = 32
        to = ref.OuterMean(dim=dim).eval()
        jo = mine.OuterMean(dim=dim)
        x = rand_t(1, 4, 6, dim)
        with torch.no_grad():
            want = to(x)
        params = {"params": {
            "LayerNorm_0": layernorm(to.norm),
            "left_proj": linear(None, to.left_proj),
            "right_proj": linear(None, to.right_proj),
            "proj_out": linear(None, to.proj_out),
        }}
        got = jo.apply(params, t2j(x))
        assert np.allclose(np.asarray(got), want.numpy(), atol=1e-5)
