"""Feature-pipeline disaggregation tests (ISSUE 10): the feature key
(stability, config-digest misses), the FeatureCache tier (LRU, disk
roundtrip, quarantine), the FeaturePool (dedup/coalescing fan-out,
cache-hit-skips-featurize, deadline shed, error fan-out,
raw-vs-pretokenized end-to-end equality), the off-by-default scrubbed
serve_stats() identity, the raw front-door/fleet seams, and the
memory-aware preemption admission satellite.

Scheduler-level tests run against a stub executor (no model, no XLA),
same pattern as tests/test_obs.py — featurization is pure host-side
numpy, so nothing here needs the real fold.
"""

import json
import threading
import time

import numpy as np
import pytest

from alphafold2_tpu import obs
from alphafold2_tpu.cache import (FeatureCache, FeaturizedInput,
                                  decode_features, encode_features,
                                  feature_key)
from alphafold2_tpu.data.featurize import detokenize, tokenize
from alphafold2_tpu.obs.trace import NULL_TRACE
from alphafold2_tpu.serve import (BucketPolicy, FeaturePool, FoldRequest,
                                  PipelineScheduler, RawFoldRequest,
                                  Scheduler, SchedulerConfig,
                                  ServeMetrics, featurize_raw,
                                  featurizer_config_digest)


class _StubResult:
    def __init__(self, coords, confidence):
        self.coords = coords
        self.confidence = confidence


class _StubExecutor:
    """Executor-shaped stand-in whose output is a pure function of the
    batch content — so two serving paths fed identical tokens must
    produce byte-identical responses."""

    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s

    def run(self, batch, num_recycles, trace=NULL_TRACE):
        with trace.span("fold"):
            if self.delay_s:
                time.sleep(self.delay_s)
            seq = np.asarray(batch["seq"], np.float32)
            coords = np.repeat(seq[..., None], 3, axis=-1)
            confidence = (seq % 7 + 1.0) / 8.0
            return _StubResult(coords, confidence)

    def stats(self):
        return {"hits": 0, "misses": 0, "evictions": 0, "resident": 0,
                "max_entries": 1, "keys": []}


def _scheduler(pool=None, tracer=None, registry=None, **cfg):
    reg = registry or obs.MetricsRegistry()
    config = SchedulerConfig(**{"max_batch_size": 2, "max_wait_ms": 10.0,
                                "num_recycles": 0, **cfg})
    return Scheduler(_StubExecutor(), BucketPolicy((16,)), config,
                     ServeMetrics(registry=reg), registry=reg,
                     tracer=tracer, feature_pool=pool)


SEQ = "MKVLAARNDC"
MSA = ["MKVLAARNDC", "MKVLA-RNDC", "MKVRAARND-"]


@pytest.mark.quick
class TestFeatureKey:
    def test_stable_and_case_canonical(self):
        k1 = feature_key(SEQ, MSA)
        assert k1 == feature_key(SEQ, MSA)
        assert k1 == feature_key(SEQ.lower(), MSA)
        assert k1 == feature_key(f"  {SEQ} ", MSA)

    def test_content_splits_key(self):
        base = feature_key(SEQ, MSA)
        assert feature_key(SEQ) != base
        assert feature_key(SEQ[:-1], [r[:-1] for r in MSA]) != base
        assert feature_key(SEQ, MSA[:2]) != base

    def test_config_digest_misses_cleanly(self):
        """A featurizer config change must split the key: a cache
        written under the old digest can never serve the new mapping."""
        k_now = feature_key(SEQ, MSA,
                            config_digest=featurizer_config_digest())
        k_other = feature_key(SEQ, MSA, config_digest="other-config")
        assert k_now != k_other
        assert feature_key(SEQ, MSA) != k_now   # "" default differs too

    def test_token_and_string_forms_key_separately(self):
        # the digest covers the raw content the featurizer reads; the
        # downstream fold_key over the RESULTING tokens unifies them
        assert feature_key(tokenize(SEQ)) != feature_key(SEQ)
        t = feature_key(tokenize(SEQ))
        assert t == feature_key(tokenize(SEQ))

    def test_invalid_inputs_raise(self):
        with pytest.raises(ValueError):
            feature_key("")
        with pytest.raises(ValueError):
            feature_key(np.zeros((2, 3), np.int32))


@pytest.mark.quick
class TestFeaturizeRaw:
    def test_string_and_tokens_agree(self):
        a = featurize_raw(RawFoldRequest(SEQ, msa=MSA))
        b = featurize_raw(RawFoldRequest(
            tokenize(SEQ), msa=np.stack([tokenize(r) for r in MSA])))
        np.testing.assert_array_equal(a.seq, b.seq)
        np.testing.assert_array_equal(a.msa, b.msa)

    def test_detokenize_roundtrip(self):
        tokens = np.arange(21, dtype=np.int32)    # every token id
        np.testing.assert_array_equal(
            featurize_raw(RawFoldRequest(detokenize(tokens))).seq,
            tokens)

    def test_misaligned_msa_raises(self):
        with pytest.raises(ValueError, match="aligned length"):
            featurize_raw(RawFoldRequest(SEQ, msa=["MKV"]))
        with pytest.raises(ValueError):
            featurize_raw(RawFoldRequest(SEQ, msa=np.zeros((2, 3),
                                                           np.int32)))


@pytest.mark.quick
class TestFeatureCache:
    def test_roundtrip_and_validation(self):
        key = feature_key(SEQ, MSA)
        value = featurize_raw(RawFoldRequest(SEQ, msa=MSA))
        data = encode_features(key, value)
        back = decode_features(key, data)
        np.testing.assert_array_equal(back.seq, value.seq)
        np.testing.assert_array_equal(back.msa, value.msa)
        with pytest.raises(Exception):
            decode_features("other-key", data)
        with pytest.raises(Exception):
            decode_features(key, data[:40])

    def test_memory_lru_eviction_bytes_accounting(self):
        reg = obs.MetricsRegistry()
        cache = FeatureCache(max_entries=2, registry=reg)
        for i in range(3):
            cache.put(f"k{i}", np.full(4, i, np.int32))
        assert len(cache) == 2
        assert cache.get("k0") is None       # LRU evicted
        assert cache.evictions == 1
        assert cache.bytes_resident == 2 * 16

    def test_disk_tier_roundtrip_and_promotion(self, tmp_path):
        reg = obs.MetricsRegistry()
        d = str(tmp_path / "feat")
        a = FeatureCache(disk_dir=d, registry=reg)
        key = feature_key(SEQ, MSA)
        feats = featurize_raw(RawFoldRequest(SEQ, msa=MSA))
        a.put(key, feats.seq, feats.msa)
        # a fresh instance over the same dir: disk hit, promoted to mem
        b = FeatureCache(disk_dir=d, registry=reg)
        got = b.get(key)
        assert got is not None
        np.testing.assert_array_equal(got.seq, feats.seq)
        assert b.disk_hits == 1
        assert b.get(key) is not None        # now memory-resident
        assert b.hits == 2

    def test_corrupt_disk_entry_quarantined(self, tmp_path):
        import os
        reg = obs.MetricsRegistry()
        d = str(tmp_path / "feat")
        cache = FeatureCache(disk_dir=d, registry=reg)
        key = feature_key(SEQ)
        cache.put(key, tokenize(SEQ))
        path = cache._path(key)
        with open(path, "wb") as fh:
            fh.write(b"garbage")
        fresh = FeatureCache(disk_dir=d, registry=reg)
        assert fresh.get(key) is None
        assert fresh.disk_errors == 1
        assert not os.path.exists(path)
        assert os.path.exists(path + ".quarantined")


class TestFeaturePool:
    def test_dedup_coalescing_fan_out(self):
        """N identical raw jobs in flight: ONE featurize execution,
        N-1 coalesced, every ticket resolves ok with exact arrays."""
        reg = obs.MetricsRegistry()
        calls = []

        def counting(raw):
            calls.append(raw.request_id)
            time.sleep(0.1)
            return featurize_raw(raw)

        pool = FeaturePool(workers=2, cache=FeatureCache(registry=reg),
                           featurize_fn=counting, registry=reg)
        sched = _scheduler(pool, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            tickets = [pipe.submit_raw(RawFoldRequest(SEQ, msa=MSA))
                       for _ in range(4)]
            resps = [t.result(timeout=30) for t in tickets]
        assert all(r.ok for r in resps)
        assert len(calls) == 1                 # zero duplicate featurize
        snap = pool.snapshot()
        assert snap["executions"] == 1
        assert snap["coalesced"] == 3
        for r in resps:
            assert r.coords.shape == (len(SEQ), 3)

    def test_cache_hit_skips_featurize(self):
        reg = obs.MetricsRegistry()
        calls = []

        def counting(raw):
            calls.append(1)
            return featurize_raw(raw)

        pool = FeaturePool(workers=1, cache=FeatureCache(registry=reg),
                           featurize_fn=counting, registry=reg)
        sched = _scheduler(pool, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            assert pipe.submit_raw(
                RawFoldRequest(SEQ, msa=MSA)).result(timeout=30).ok
            assert pipe.submit_raw(
                RawFoldRequest(SEQ, msa=MSA)).result(timeout=30).ok
        assert len(calls) == 1
        snap = pool.snapshot()
        assert snap["cache_hits"] == 1
        assert snap["executions"] == 1
        assert reg.counter(
            "serve_featurize_cache_hits_total").value() == 1

    def test_raw_vs_pretokenized_end_to_end_equality(self):
        """The pipeline is a pure re-plumbing: a raw submission must
        serve byte-identical coords/confidence to the classic
        tokenized submit of the same content."""
        reg = obs.MetricsRegistry()
        pool = FeaturePool(workers=2, cache=FeatureCache(registry=reg),
                           registry=reg)
        sched = _scheduler(pool, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            raw_resp = pipe.submit_raw(
                RawFoldRequest(SEQ, msa=MSA)).result(timeout=30)
        sched2 = _scheduler()
        with sched2:
            tok_resp = sched2.submit(FoldRequest(
                seq=tokenize(SEQ),
                msa=np.stack([tokenize(r) for r in MSA]))).result(
                    timeout=30)
        assert raw_resp.ok and tok_resp.ok
        np.testing.assert_array_equal(raw_resp.coords, tok_resp.coords)
        np.testing.assert_array_equal(raw_resp.confidence,
                                      tok_resp.confidence)

    def test_feature_deadline_shed(self):
        """A raw job whose deadline dies while features cook is shed
        WITHOUT touching the fold queue."""
        reg = obs.MetricsRegistry()
        pool = FeaturePool(workers=1, latency_s=0.2, registry=reg)
        sched = _scheduler(pool, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            resp = pipe.submit_raw(RawFoldRequest(
                SEQ, deadline_s=0.02)).result(timeout=30)
        assert resp.status == "shed"
        assert "feature_deadline_exceeded" in resp.error
        assert pool.snapshot()["shed"] == 1
        assert sched.serve_stats()["enqueued"] == 0

    def test_featurize_error_fans_out_to_coalesced(self):
        """A failing featurize resolves the leader AND every coalesced
        waiter as error — nobody hangs."""
        reg = obs.MetricsRegistry()

        def boom(raw):
            time.sleep(0.05)
            raise RuntimeError("featurize boom")

        pool = FeaturePool(workers=1, featurize_fn=boom, registry=reg)
        sched = _scheduler(pool, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            tickets = [pipe.submit_raw(RawFoldRequest(SEQ))
                       for _ in range(3)]
            resps = [t.result(timeout=30) for t in tickets]
        assert all(r.status == "error" for r in resps)
        assert all("featurize boom" in r.error for r in resps)
        assert pool.snapshot()["errors"] == 3
        assert reg.counter("serve_featurize_errors_total").value() == 3

    def test_progress_chains_through_pipeline(self):
        """Progressive updates published on the inner fold ticket
        reach the raw caller's ticket."""
        reg = obs.MetricsRegistry()
        pool = FeaturePool(workers=1, registry=reg)
        sched = _scheduler(pool, registry=reg)
        seen = []
        with PipelineScheduler(sched, pool) as pipe:
            ticket = pipe.submit_raw(RawFoldRequest(SEQ))
            ticket.add_progress_callback(lambda p: seen.append(p))
            assert ticket.result(timeout=30).ok
        # the stub fold publishes no progress; exercise the chain
        # directly: outer tickets must expose the inner publication
        assert ticket.progress() == seen

    def test_preseeded_cache_serves_without_execution(self):
        """Claim-then-check ordering: a key already in the cache (a
        prior process, a racing leader that finished first) serves at
        zero executions, and the transient leadership claim is
        released for the next key."""
        reg = obs.MetricsRegistry()
        cache = FeatureCache(registry=reg)
        feats = featurize_raw(RawFoldRequest(SEQ, msa=MSA))
        cache.put(feature_key(SEQ, MSA,
                              config_digest=featurizer_config_digest()),
                  feats.seq, feats.msa)
        pool = FeaturePool(workers=1, cache=cache, registry=reg)
        sched = _scheduler(pool, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            assert pipe.submit_raw(
                RawFoldRequest(SEQ, msa=MSA)).result(timeout=30).ok
        snap = pool.snapshot()
        assert snap["executions"] == 0 and snap["cache_hits"] == 1
        with pool._lock:
            assert not pool._inflight        # claim fully released

    def test_overlength_raw_job_resolves_and_traces(self, tmp_path):
        """A raw job whose featurized length exceeds the largest
        bucket fails at the fold submit's fail-fast — the ticket must
        still resolve AND its trace must still emit (no silent
        disappearance from obs)."""
        reg = obs.MetricsRegistry()
        tracer = obs.Tracer(jsonl_path=str(tmp_path / "t.jsonl"))
        pool = FeaturePool(workers=1, registry=reg)
        sched = _scheduler(pool, tracer=tracer, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            resp = pipe.submit_raw(
                RawFoldRequest("M" * 64)).result(timeout=30)
        assert resp.status == "error"
        assert "rejected after featurize" in resp.error
        tracer.close()
        (rec,) = [json.loads(line)
                  for line in open(tmp_path / "t.jsonl")]
        assert rec["status"] == "error"
        assert "featurize" in [s["name"] for s in rec["spans"]]

    def test_featurize_span_in_trace(self, tmp_path):
        reg = obs.MetricsRegistry()
        tracer = obs.Tracer(jsonl_path=str(tmp_path / "t.jsonl"))
        pool = FeaturePool(workers=1, cache=FeatureCache(registry=reg),
                           registry=reg)
        sched = _scheduler(pool, tracer=tracer, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            assert pipe.submit_raw(
                RawFoldRequest(SEQ, msa=MSA)).result(timeout=30).ok
        tracer.close()
        recs = [json.loads(line)
                for line in open(tmp_path / "t.jsonl")]
        (rec,) = recs
        names = [s["name"] for s in rec["spans"]]
        assert names[0] == "featurize"
        assert "submit" in names and "fold" in names
        assert rec["status"] == "ok"

    def test_queue_depth_gauge(self):
        reg = obs.MetricsRegistry()
        release = threading.Event()

        def gated(raw):
            release.wait(10)
            return featurize_raw(raw)

        pool = FeaturePool(workers=1, featurize_fn=gated, registry=reg)
        sched = _scheduler(pool, registry=reg)
        with PipelineScheduler(sched, pool) as pipe:
            tickets = [pipe.submit_raw(RawFoldRequest(detokenize(
                np.full(8, i, np.int32)))) for i in range(3)]
            deadline = time.monotonic() + 5
            while reg.gauge("serve_featurize_queue_depth").value() < 3 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert reg.gauge(
                "serve_featurize_queue_depth").value() == 3
            release.set()
            for t in tickets:
                assert t.result(timeout=30).ok
        assert reg.gauge("serve_featurize_queue_depth").value() == 0


class TestOffByDefault:
    def test_submit_raw_without_pool_inline(self):
        """No pool: submit_raw featurizes inline and behaves exactly
        like tokenize + submit."""
        sched = _scheduler()
        with sched:
            resp = sched.submit_raw(
                RawFoldRequest(SEQ, msa=MSA)).result(timeout=30)
        assert resp.ok and resp.source == "fold"
        assert "featurize" not in sched.serve_stats()

    def test_scrubbed_serve_stats_identity(self):
        """The off switch: feature_pool=None must leave serve_stats()
        byte-identical between a submit_raw workload and the classic
        tokenized-submit workload of the same content (scrubbed of
        wall-clock fields, same rule as the mesh/transport identity
        tests)."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        def run_one(use_raw):
            sched = _scheduler()
            with sched:
                for s in (SEQ, SEQ[:8], SEQ[:6]):
                    if use_raw:
                        t = sched.submit_raw(RawFoldRequest(s))
                    else:
                        t = sched.submit(FoldRequest(seq=tokenize(s)))
                    assert t.result(timeout=30).ok
            return scrub(sched.serve_stats())

        a = run_one(True)
        b = run_one(False)
        assert json.dumps(a, sort_keys=True, default=str) \
            == json.dumps(b, sort_keys=True, default=str)
        assert "featurize" not in a


class TestFleetRawPath:
    def test_rpc_raw_roundtrip(self):
        from alphafold2_tpu.fleet.rpc import (decode_raw_request,
                                              encode_raw_request)
        raw = RawFoldRequest(SEQ, msa=MSA, priority=2, deadline_s=1.5)
        body, headers = encode_raw_request(raw)
        assert headers["Content-Type"] == "application/json"
        back = decode_raw_request(body, headers)
        assert back.seq == SEQ and list(back.msa) == MSA
        assert back.priority == 2 and back.deadline_s == 1.5
        assert back.request_id == raw.request_id
        # token form travels as int lists
        raw_t = RawFoldRequest(tokenize(SEQ))
        body, headers = encode_raw_request(raw_t)
        back = decode_raw_request(body, headers)
        np.testing.assert_array_equal(np.asarray(back.seq),
                                      tokenize(SEQ))

    def test_malformed_raw_body_is_value_error(self):
        """Every malformed-content failure must be ValueError (the
        front door's 400), never TypeError (a 500 failover layers
        would retry fleet-wide)."""
        from alphafold2_tpu.fleet.rpc import decode_raw_request
        for body in (b'{"seq": null}', b'{"seq": {"a": 1}}',
                     b'{"seq": "MKV", "msa": 3}', b'not json', b'{}'):
            with pytest.raises(ValueError):
                decode_raw_request(body, {})

    def test_frontdoor_accepts_raw_json_body(self):
        """POST /v1/submit with a JSON body featurizes replica-side
        and serves the fold over the normal long-poll."""
        from alphafold2_tpu.fleet.frontdoor import FrontDoorServer
        from alphafold2_tpu.fleet.rpc import HttpTransport

        reg = obs.MetricsRegistry()
        sched = _scheduler(registry=reg)
        sched.start()
        server = FrontDoorServer(sched, replica_id="r0", metrics=reg)
        try:
            with server:
                transport = HttpTransport(server.url, metrics=reg)
                ticket = transport.submit_raw(
                    RawFoldRequest(SEQ, msa=MSA))
                resp = ticket.result(timeout=30)
        finally:
            sched.stop()
        assert resp.ok, (resp.status, resp.error)
        assert resp.coords.shape == (len(SEQ), 3)
        # byte-equal to the in-process tokenized fold of the same content
        sched2 = _scheduler()
        with sched2:
            local = sched2.submit(FoldRequest(
                seq=tokenize(SEQ),
                msa=np.stack([tokenize(r) for r in MSA]))).result(
                    timeout=30)
        np.testing.assert_array_equal(resp.coords, local.coords)

    def test_fleet_routes_raw_by_feature_key(self):
        """InProcessFleet with feature pools: every unique raw key
        featurizes exactly once FLEET-WIDE (the owner does it), and
        cross-replica raw jobs take the forward hop."""
        from alphafold2_tpu import fleet

        reg = obs.MetricsRegistry()
        fl = fleet.InProcessFleet(
            lambda: _StubExecutor(), BucketPolicy((16,)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                            num_recycles=0),
            n_replicas=2, model_tag="t", registry=reg,
            feature_pool_factory=lambda i: FeaturePool(
                workers=1, cache=FeatureCache(registry=reg),
                registry=reg))
        seqs = [detokenize(np.asarray(
            np.random.default_rng(s).integers(0, 21, 10), np.int32))
            for s in range(4)]
        with fl:
            tickets = [fl.submit_raw(RawFoldRequest(s), replica=i % 2)
                       for i, s in enumerate(seqs * 3)]
            for t in tickets:
                r = t.result(timeout=30)
                assert r.ok, (r.status, r.error)
        pools = [r.scheduler.feature_pool for r in fl.replicas]
        assert sum(p.executions for p in pools) == len(seqs)
        assert sum(p.forwarded for p in pools) > 0


class TestMemoryAwarePreemption:
    """ISSUE 10 satellite: the leased-yield admission guard prices the
    suspended loop's HBM-resident carry."""

    def _mesh_scheduler(self, hbm_gb, recycle=True):
        from alphafold2_tpu.serve import (FoldMemoryModel, MeshPolicy,
                                          RecyclePolicy)

        memory = FoldMemoryModel(param_bytes=0, dim=64, heads=8,
                                 hbm_bytes_per_device=int(
                                     hbm_gb * (1 << 30)))
        policy = MeshPolicy({16: 1}, devices=[object() for _ in range(2)],
                            memory=memory)
        reg = obs.MetricsRegistry()
        sched = Scheduler(
            _StubExecutor(), BucketPolicy((16,)),
            SchedulerConfig(max_batch_size=4, max_wait_ms=10.0,
                            num_recycles=2, msa_depth=0),
            ServeMetrics(registry=reg), registry=reg,
            mesh_policy=policy,
            recycle_policy=(RecyclePolicy(preempt=True) if recycle
                            else None))
        return sched, reg

    def test_carry_bytes_term(self):
        from alphafold2_tpu.serve import FoldMemoryModel

        m = FoldMemoryModel(param_bytes=0, dim=32)
        assert m.carry_bytes(64, 2) > 0
        # pairwise term shards over the slice
        assert m.carry_bytes(64, 2, chips=4) < m.carry_bytes(64, 2)
        # fold_bytes(carry_recyclables=True) is exactly base + carry
        assert m.fold_bytes(64, 2, 0, carry_recyclables=True) \
            == m.fold_bytes(64, 2, 0) + m.carry_bytes(64, 2)

    def test_admits_with_headroom_refuses_without(self):
        sched_big, _ = self._mesh_scheduler(hbm_gb=64.0)
        assert sched_big._preempt_hbm_admits(16, 16)
        # tiny budget: urgent footprint + suspended carry cannot
        # co-reside on one device
        sched_small, _ = self._mesh_scheduler(hbm_gb=0.0005)
        assert not sched_small._preempt_hbm_admits(16, 16)
        # no urgent bucket / no memory model -> vacuously admitted
        assert sched_small._preempt_hbm_admits(16, None)
        sched_small.mesh_policy.memory = None
        assert sched_small._preempt_hbm_admits(16, 16)

    def test_unpinned_msa_depth_prices_urgent_entry_depth(self):
        """With config.msa_depth=None the admission must price the
        urgent entry's OWN advertised MSA depth, not zero — a deep-MSA
        urgent batch that only fits without its MSA term must be
        refused."""
        from alphafold2_tpu.serve import (FoldMemoryModel, MeshPolicy,
                                          RecyclePolicy)

        memory = FoldMemoryModel(param_bytes=0, dim=64, heads=8)
        policy = MeshPolicy({16: 1},
                            devices=[object() for _ in range(2)],
                            memory=memory)
        reg = obs.MetricsRegistry()
        sched = Scheduler(
            _StubExecutor(), BucketPolicy((16,)),
            SchedulerConfig(max_batch_size=4, max_wait_ms=10.0,
                            num_recycles=2, msa_depth=None),
            ServeMetrics(registry=reg), registry=reg,
            mesh_policy=policy,
            recycle_policy=RecyclePolicy(preempt=True))
        base = memory.fold_bytes(16, 4, 0, carry_recyclables=True) \
            + memory.carry_bytes(16, 4)
        deep = memory.fold_bytes(16, 4, 4096, carry_recyclables=True) \
            + memory.carry_bytes(16, 4)
        assert deep > base
        memory.hbm_bytes_per_device = (base + deep) // 2
        assert sched._preempt_hbm_admits(16, 16, urgent_msa=0)
        assert sched._preempt_hbm_admits(16, 16, urgent_msa=None)
        assert not sched._preempt_hbm_admits(16, 16, urgent_msa=4096)

    def test_leased_yield_refused_and_counted(self):
        """Saturated pool + tight urgent deadline, but no HBM headroom:
        _maybe_preempt must keep the lease, count the refusal, and
        never release/re-acquire."""
        from alphafold2_tpu.serve.scheduler import _Entry

        sched, reg = self._mesh_scheduler(hbm_gb=0.0005)
        alloc = sched._allocator
        lease = alloc.acquire((1, 1))
        other = alloc.acquire((1, 1))     # pool saturated
        assert not alloc.can_allocate((1, 1))
        with sched._cond:
            sched._pending_tightest = time.monotonic() + 0.5
            sched._pending_tightest_chips = 1
            sched._pending_tightest_bucket = 16
        entry = _Entry(FoldRequest(seq=np.zeros(8, np.int32)), 16)
        out = sched._maybe_preempt([entry], lease, gap=1, bucket_len=16)
        assert out is lease               # kept, not yielded
        assert sched._n_preempt_hbm_refusals == 1
        assert sched._n_preemptions == 0
        assert reg.counter(
            "serve_preempt_hbm_refusals_total").value() == 1
        stats = sched.serve_stats()
        assert stats["recycle"]["preempt_hbm_refusals"] == 1
        alloc.release(lease)
        alloc.release(other)

    def test_leased_yield_proceeds_with_headroom(self):
        """Same saturation, big budget: the yield fires (preemption
        counted, slice released for the gap then re-acquired)."""
        from alphafold2_tpu.serve.scheduler import _Entry

        sched, reg = self._mesh_scheduler(hbm_gb=64.0)
        alloc = sched._allocator
        lease = alloc.acquire((1, 1))
        other = alloc.acquire((1, 1))
        with sched._cond:
            sched._pending_tightest = time.monotonic() + 0.5
            sched._pending_tightest_chips = 1
            sched._pending_tightest_bucket = 16
        entry = _Entry(FoldRequest(seq=np.zeros(8, np.int32)), 16)
        out = sched._maybe_preempt([entry], lease, gap=1, bucket_len=16)
        # the SAME lease object, re-armed over the same span (ISSUE 14:
        # acquire_span used to mint a new object, stranding the span on
        # failure paths that held the original reference)
        assert out is lease and out.held
        assert out.start == lease.start
        assert sched._n_preemptions == 1
        assert sched._n_preempt_hbm_refusals == 0
        alloc.release(out)
        alloc.release(other)
