"""Spot-preemptible serving tests (ISSUE 20): notice sources (file /
signal / metadata-stub) and the PreemptionWatcher, the scheduler's
grace-budgeted reclaim drain (finish-when-it-fits, spill-when-it-
cannot, queued work resolved "preempted"), the orphan manifest
publish/read/clear roundtrip over the shared object-store backend, the
survivor-side adoption resume (byte-equal coords, recycles lost <=
checkpoint_every), the controller's orphan adoption (sweep + notice
sources, retry-until-manifest, rejoin cancellation, least-loaded
survivor via POST /admin/adopt), fast failover on announced reclaim
(healthz 503 + FleetClient / PeerCacheClient immediate mark-down), the
autoscaler's preemption-window burn suppression, the XLA error-payload
classifier and its RetryPolicy seam, and the feature-off identity pins.

Scheduler tests run the pytree-carry scripted stub convention from
test_checkpoints.py (coords accumulate multiplicatively, so a refold
from zero CANNOT byte-match a resumed loop); an optional per-step sleep
makes the grace-window fit test deterministic. The multi-process chaos
e2e (notice + grace kill, 0 lost folds) is `slow`-marked — the
serve_smoke.sh phase 18 story in miniature.
"""

import http.server
import json
import os
import signal
import socket
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import fleet
from alphafold2_tpu.cache.checkpoints import (CheckpointStore,
                                              RowCheckpoint,
                                              clear_manifest,
                                              manifest_key, read_manifest)
from alphafold2_tpu.fleet.controlplane import FleetController
from alphafold2_tpu.fleet.frontdoor import FrontDoorServer
from alphafold2_tpu.fleet.object_store import FilesystemObjectStore
from alphafold2_tpu.fleet.peer import PeerCacheClient
from alphafold2_tpu.fleet.procfleet import FleetClient, ProcFleet
from alphafold2_tpu.fleet.registry import ReplicaRegistry
from alphafold2_tpu.fleet.scaling import (HOLD, SCALE_UP, ReplicaSignals,
                                          ScalingPolicy, decide_scale)
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, FoldRequest,
                                  RecyclePolicy, RetryPolicy, Scheduler,
                                  SchedulerConfig, ServeMetrics)
from alphafold2_tpu.serve.preemption import (DEFAULT_GRACE_S,
                                             FileNoticeSource,
                                             MetadataNoticeSource,
                                             PreemptionNotice,
                                             PreemptionWatcher,
                                             SignalNoticeSource)
from alphafold2_tpu.serve.xla_errors import attributed_rows, classify


# -- pytree-carry step stub (test_checkpoints.py convention) ----------


class _PmState:
    def __init__(self, coords, confidence, ids, counts):
        self.coords = coords
        self.confidence = confidence
        self.ids = ids
        self.counts = counts


jax.tree_util.register_pytree_node(
    _PmState,
    lambda s: ((s.coords, s.confidence, s.ids, s.counts), None),
    lambda aux, ch: _PmState(*ch))


class _PmStub:
    """Scripted step executor whose carry is a real pytree. step_sleep_s
    slows every recycle so a grace window decisively cannot fit the
    remaining loop (the spill-over-finish decision under test)."""

    def __init__(self, step_sleep_s=0.0):
        self.calls = []
        self.step_sleep_s = float(step_sleep_s)

    def run_init(self, batch, trace=None, devices=None, mesh_shape=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        self.calls.append(("init", [int(i) for i in seq[:, 0]]))
        return _PmState(jnp.zeros((b, n, 3), jnp.float32),
                        jnp.zeros((b, n), jnp.float32),
                        jnp.asarray(seq[:, 0], jnp.int32),
                        jnp.zeros((b,), jnp.int32))

    def run_init_rows(self, batch, state, row_mask, trace=None,
                      devices=None, mesh_shape=None, span_attrs=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        mask = jnp.asarray(np.asarray(row_mask))
        self.calls.append(("init_rows", int(np.asarray(row_mask).sum())))
        return _PmState(
            jnp.where(mask[:, None, None],
                      jnp.zeros((b, n, 3), jnp.float32), state.coords),
            jnp.where(mask[:, None],
                      jnp.zeros((b, n), jnp.float32), state.confidence),
            jnp.where(mask, jnp.asarray(seq[:, 0], jnp.int32), state.ids),
            jnp.where(mask, 0, state.counts))

    def run_step(self, batch, state, recycle_index, trace=None,
                 devices=None, mesh_shape=None, span_attrs=None):
        if self.step_sleep_s:
            time.sleep(self.step_sleep_s)
        self.calls.append(("step", int(recycle_index)))
        return _PmState(
            state.coords * jnp.float32(1.01) + jnp.float32(1.0)
            + state.ids[:, None, None].astype(jnp.float32) * 0.001,
            state.confidence, state.ids, state.counts + 1)

    def stats(self):
        return {"calls": len(self.calls)}

    def steps(self):
        return sum(1 for c in self.calls if c[0] == "step")


def _sched(stub, spill_dir, num_recycles=6, registry=None,
           model_tag="pm@1", **kw):
    registry = registry or MetricsRegistry()
    return Scheduler(
        stub, BucketPolicy((32,)),
        SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                        num_recycles=num_recycles, msa_depth=0,
                        poll_ms=2.0),
        recycle_policy=RecyclePolicy(converge_tol=0.0),
        retry=RetryPolicy(checkpoint_every=1,
                          checkpoint_spill=spill_dir or "",
                          backoff_base_s=0.0, jitter=0.0),
        metrics=ServeMetrics(registry=registry), registry=registry,
        model_tag=model_tag, **kw)


def _req(token=7, length=12):
    return FoldRequest(seq=np.full(length, token, np.int32))


def _wait(predicate, timeout_s=30.0, interval_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# -- front-door fixtures (test_controlplane.py convention) ------------


class _OkExecutor:
    def __init__(self):
        self.calls = 0

    def run(self, batch, num_recycles, trace=None):
        self.calls += 1
        b, n = batch["seq"].shape

        class R:
            coords = np.zeros((b, n, 3), np.float32)
            confidence = np.full((b, n), 0.5, np.float32)

        return R()

    def stats(self):
        return {"calls": self.calls}


def _door_scheduler(model_tag="pm"):
    return Scheduler(_OkExecutor(), BucketPolicy((16,)),
                     SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                                     poll_ms=2.0, msa_depth=0),
                     model_tag=model_tag, registry=MetricsRegistry())


class _Door:
    def __init__(self, rollout=None, model_tag="pm", replica_id="fd0"):
        self.metrics = MetricsRegistry()
        self.scheduler = _door_scheduler(model_tag=model_tag)
        self.server = FrontDoorServer(self.scheduler, rollout=rollout,
                                      replica_id=replica_id,
                                      metrics=self.metrics)

    def __enter__(self):
        self.scheduler.start()
        self.server.start()
        return self

    def __exit__(self, *exc):
        self.server.stop()
        self.scheduler.stop()


def _fold_req(seed=0, n=12):
    rng = np.random.default_rng(seed)
    return FoldRequest(seq=rng.integers(0, 20, size=n).astype(np.int32))


class _MiniFleet:
    """In-process actuator: real FrontDoorServers over localhost HTTP,
    stub executors, fleet verbs as plain method calls."""

    def __init__(self, tag="v1"):
        self.tag = tag
        self.doors = {}                # rid -> _Door
        self.extra_endpoints = {}      # rid -> url (fakes/dead ports)
        self.scale_down_calls = []
        self._next = 0

    def spawn(self):
        rid = f"r{self._next}"
        self._next += 1
        rollout = fleet.RolloutState(self.tag,
                                     registry=MetricsRegistry())
        door = _Door(rollout=rollout, replica_id=rid)
        door.__enter__()
        self.doors[rid] = door
        return rid

    def endpoints(self):
        out = {rid: d.server.url for rid, d in self.doors.items()}
        out.update(self.extra_endpoints)
        return out

    def scale_up(self):
        return self.spawn()

    def scale_down(self, rid):
        self.scale_down_calls.append(rid)
        return self.remove(rid)

    def remove(self, rid):
        door = self.doors.pop(rid, None)
        if door is None:
            return self.extra_endpoints.pop(rid, None) is not None
        door.__exit__()
        return True

    def key_log_paths(self):
        return {}

    def stop(self):
        for rid in list(self.doors):
            self.remove(rid)
        self.extra_endpoints.clear()


def _controller(mini, clk, **kwargs):
    kwargs.setdefault("policy", ScalingPolicy(min_replicas=1,
                                              max_replicas=4,
                                              cooldown_s=5.0))
    kwargs.setdefault("registry", MetricsRegistry())
    kwargs.setdefault("heartbeat_timeout_s", 5.0)
    kwargs.setdefault("probe_timeout_s", 5.0)
    return FleetController(mini, clock=lambda: clk[0], **kwargs)


def _put_manifest(backend, rid, orphans, tag="v1"):
    man = {"schema": "orphans-v1", "replica_id": rid, "model_tag": tag,
           "published_s": time.time(), "orphans": orphans}
    backend.put(manifest_key(rid), json.dumps(man).encode("utf-8"))
    return man


# -- XLA error-payload classifier -------------------------------------


@pytest.mark.quick
class TestXlaErrors:
    def test_transient_shapes(self):
        for payload, reason in (
                ("RESOURCE_EXHAUSTED: Out of memory allocating 2.1GiB",
                 "resource_exhausted"),
                ("Execution failed: out of memory allocating 128 bytes",
                 "hbm_oom"),
                ("DEADLINE_EXCEEDED: fold took too long",
                 "deadline_exceeded"),
                ("UNAVAILABLE: socket closed", "unavailable"),
                ("ABORTED: slice became unhealthy mid-step", "aborted"),
                ("TPU worker terminated: host maintenance event",
                 "tpu_reclaim")):
            v = classify(payload)
            assert v is not None and v.transient, payload
            assert v.reason == reason

    def test_deterministic_shapes(self):
        for payload, reason in (
                ("INVALID_ARGUMENT: operand shapes do not match",
                 "invalid_argument"),
                ("FAILED_PRECONDITION: buffer donated twice",
                 "failed_precondition"),
                ("Check failed: lhs.dim(0) == rhs.dim(0)",
                 "check_failed"),
                ("TPU program abort at tag 7", "program_abort"),
                ("INTERNAL: during HLO pass pipeline", "xla_internal")):
            v = classify(payload)
            assert v is not None and not v.transient, payload
            assert v.reason == reason

    def test_transient_checked_before_deterministic(self):
        # an ABORTED status wrapping a CHECK message is still the
        # infrastructure's abort — retryable, not a program bug
        v = classify("ABORTED: Check failed: slice heartbeat")
        assert v is not None and v.transient

    def test_row_attribution_rides_the_verdict(self):
        v = classify("non-finite values detected at batch index 3")
        assert v is not None and not v.transient
        assert v.reason == "non_finite" and v.rows == (3,)

    def test_attributed_rows_dedup_and_sort(self):
        assert attributed_rows(
            "row=5 then batch index 2 then batch row: 7, row=5 again"
        ) == (2, 5, 7)
        assert attributed_rows("no rows named here") == ()

    def test_no_opinion_and_never_raises(self):
        assert classify("perfectly ordinary message") is None
        assert classify(None) is None
        assert classify(12345) is None
        assert attributed_rows("") == ()


@pytest.mark.quick
class TestRetryXlaSeam:
    def test_classifier_extends_marker_list(self):
        # a TPU reclaim message no legacy marker matches: transient
        # only because the classifier ran
        exc = RuntimeError("TPU worker terminated: maintenance event")
        assert RetryPolicy().is_transient(exc) is True
        assert RetryPolicy(xla_classify=False).is_transient(exc) is False

    def test_deterministic_verdict_stays_false(self):
        exc = RuntimeError("Check failed: lhs.rank() == 2")
        assert RetryPolicy().is_transient(exc) is False

    def test_legacy_markers_keep_precedence(self):
        # marker list already says transient; a deterministic-looking
        # suffix must not flip the legacy verdict
        exc = RuntimeError("UNAVAILABLE: Check failed downstream")
        assert RetryPolicy().is_transient(exc) is True
        assert RetryPolicy(xla_classify=False).is_transient(exc) is True


# -- notice sources ---------------------------------------------------


class _MetaHandler(http.server.BaseHTTPRequestHandler):
    body = b"TRUE"
    flavors = []

    def do_GET(self):
        type(self).flavors.append(self.headers.get("Metadata-Flavor"))
        self.send_response(200)
        self.send_header("Content-Length", str(len(self.body)))
        self.end_headers()
        self.wfile.write(self.body)

    def log_message(self, *a):
        pass


@pytest.mark.quick
class TestNoticeSources:
    def test_file_missing_then_json(self, tmp_path):
        path = str(tmp_path / "preempt.notice")
        src = FileNoticeSource(path)
        assert src.poll() is None
        with open(path, "w") as fh:
            json.dump({"grace_s": 3.5, "detail": "reclaim"}, fh)
        n = src.poll()
        assert n is not None and n.source == "file"
        assert n.grace_s == 3.5 and n.detail == "reclaim"

    def test_file_empty_and_torn_still_notice(self, tmp_path):
        empty = tmp_path / "empty.notice"
        empty.touch()
        n = FileNoticeSource(str(empty)).poll()
        assert n is not None and n.grace_s == DEFAULT_GRACE_S

        torn = tmp_path / "torn.notice"
        torn.write_text('{"grace_s": 3')   # half-written announcement
        n = FileNoticeSource(str(torn), grace_s=9.0).poll()
        assert n is not None and n.grace_s == 9.0

    def test_deadline_is_received_plus_grace(self):
        n = PreemptionNotice(source="x", grace_s=5.0, received_s=100.0)
        assert n.deadline_s == 105.0

    def test_signal_notify_seam(self):
        src = SignalNoticeSource(grace_s=7.0)
        assert src.poll() is None
        src.notify("acpi")
        n = src.poll()
        assert n is not None and n.source == "signal"
        assert n.grace_s == 7.0 and n.detail == "acpi"

    def test_signal_install_chains_previous_handler(self):
        hits = []
        prev = signal.signal(signal.SIGUSR1,
                             lambda s, f: hits.append(s))
        try:
            src = SignalNoticeSource(grace_s=9.0).install(signal.SIGUSR1)
            os.kill(os.getpid(), signal.SIGUSR1)
            assert _wait(lambda: src.poll() is not None, timeout_s=5.0)
            assert src.poll().grace_s == 9.0
            assert hits == [signal.SIGUSR1]    # previous handler ran too
        finally:
            signal.signal(signal.SIGUSR1, prev)

    def test_metadata_stub_roundtrip(self):
        _MetaHandler.flavors = []
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                              _MetaHandler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{srv.server_address[1]}/preempted"
        try:
            _MetaHandler.body = b"FALSE"
            assert MetadataNoticeSource(url=url).poll() is None
            _MetaHandler.body = b"TRUE"
            n = MetadataNoticeSource(url=url, grace_s=11.0).poll()
            assert n is not None and n.source == "metadata"
            assert n.grace_s == 11.0
            assert all(fl == "Google" for fl in _MetaHandler.flavors)
        finally:
            srv.shutdown()
            srv.server_close()

    def test_metadata_unreachable_is_no_notice(self):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        src = MetadataNoticeSource(url=f"http://127.0.0.1:{port}/x",
                                   timeout_s=0.2)
        assert src.poll() is None


class _FakeSched:
    def __init__(self):
        self.notices = []

    def preempt_notice(self, grace_s, source=""):
        self.notices.append((grace_s, source))


@pytest.mark.quick
class TestWatcher:
    def test_check_announces_exactly_once(self, tmp_path):
        path = tmp_path / "n"
        sched = _FakeSched()
        box = []
        w = PreemptionWatcher([FileNoticeSource(str(path))],
                              scheduler=sched, on_notice=box.append)
        assert w.check() is None and not sched.notices
        path.write_text(json.dumps({"grace_s": 4.0}))
        n = w.check()
        assert n is not None and sched.notices == [(4.0, "file")]
        assert [b.grace_s for b in box] == [4.0]
        # idempotent: the same notice, no second announcement
        assert w.check() is n
        assert sched.notices == [(4.0, "file")] and len(box) == 1

    def test_broken_source_never_kills_the_watch(self, tmp_path):
        class _Boom:
            def poll(self):
                raise RuntimeError("detonated")

        path = tmp_path / "n"
        path.touch()
        w = PreemptionWatcher([_Boom(), FileNoticeSource(str(path))])
        assert w.check() is not None

    def test_scheduler_exception_still_fires_callback(self, tmp_path):
        class _Angry:
            def preempt_notice(self, grace_s, source=""):
                raise RuntimeError("scheduler already stopped")

        path = tmp_path / "n"
        path.touch()
        box = []
        w = PreemptionWatcher([FileNoticeSource(str(path))],
                              scheduler=_Angry(), on_notice=box.append)
        assert w.check() is not None and len(box) == 1

    def test_thread_polls_and_stops_after_notice(self, tmp_path):
        path = tmp_path / "n"
        box = []
        w = PreemptionWatcher([FileNoticeSource(str(path))],
                              on_notice=box.append, poll_s=0.02).start()
        try:
            time.sleep(0.08)
            assert not box
            path.touch()
            assert _wait(lambda: box, timeout_s=10.0)
            assert len(box) == 1
        finally:
            w.stop()

    def test_needs_at_least_one_source(self):
        with pytest.raises(ValueError):
            PreemptionWatcher([])


# -- grace-budgeted reclaim drain -------------------------------------


class TestGraceDrain:
    def test_window_cannot_fit_spills_and_preempts(self, tmp_path):
        """~30ms steps x 24 recycles decisively overflow a 0.4s grace
        window: the in-flight batch spills at the next gap, the queued
        fold resolves without ever founding, every status reads
        "preempted", and the spilled checkpoints SURVIVE for adoption."""
        stub = _PmStub(step_sleep_s=0.03)
        reg = MetricsRegistry()
        s = _sched(stub, str(tmp_path / "spill"), num_recycles=24,
                   registry=reg)
        s.start()
        try:
            t1 = s.submit(_req(token=3))
            t2 = s.submit(_req(token=5))
            assert _wait(lambda: stub.steps() >= 2)
            t3 = s.submit(_req(token=9, length=20))   # queued behind
            complete = s.drain(grace_s=0.4)
        finally:
            s.stop()
        assert complete is True                       # no forwards
        rs = [t.result(timeout=30) for t in (t1, t2, t3)]
        assert [r.status for r in rs] == ["preempted"] * 3
        assert not any(r.ok for r in rs)
        pre = s.serve_stats()["preemption"]
        assert pre["reclaiming"] and pre["notices"] == 1
        assert pre["drain_spills"] >= 2
        names = set(reg.snapshot())
        assert "serve_preempt_notices_total" in names
        assert "serve_preempt_drain_spills_total" in names
        assert s.health().get("preempting") is True
        # the one terminal whose checkpoint is NOT discarded
        assert sum(1 for _ in s.checkpoint_store.survivors()) >= 2

    def test_window_that_fits_finishes_the_fold(self, tmp_path):
        stub = _PmStub()
        s = _sched(stub, str(tmp_path / "spill"), num_recycles=4)
        s.start()
        try:
            t = s.submit(_req())
            _wait(lambda: stub.steps() >= 1, timeout_s=30.0)
            s.drain(grace_s=30.0)
        finally:
            s.stop()
        r = t.result(timeout=30)
        assert r.ok and stub.steps() == 4
        pre = s.serve_stats()["preemption"]
        assert pre["notices"] == 1 and pre["drain_spills"] == 0

    def test_duplicate_notice_never_extends_the_deadline(self, tmp_path):
        s = _sched(_PmStub(), "", num_recycles=2)
        s.start()
        try:
            s.preempt_notice(0.5, source="file")
            first = s._reclaim_deadline
            s.preempt_notice(60.0)          # later, looser: ignored
            assert s._reclaim_deadline == first
            s.preempt_notice(0.1)           # tighter: adopted
            assert s._reclaim_deadline < first
            assert s.serve_stats()["preemption"]["source"] == "file"
        finally:
            s.stop()


# -- orphan manifest --------------------------------------------------


def _mk_ckpt(fold_key="fk", tag="pm@1", age=3, n=8):
    return RowCheckpoint(
        fold_key=fold_key, model_tag=tag, age=age,
        seq=np.arange(n, dtype=np.int32), msa=None,
        leaves=[("dev", np.arange(n * 3, dtype=np.float32)
                 .reshape(1, n, 3), None)],
        created_s=123.0)


class TestManifest:
    def test_publish_read_clear_roundtrip(self, tmp_path):
        backend = FilesystemObjectStore(str(tmp_path / "shared"))
        st = CheckpointStore(str(tmp_path / "ck"), model_tag="pm@1",
                             registry=MetricsRegistry())
        st.backend = backend
        st.put_row(_mk_ckpt(age=2))
        st.put_row(_mk_ckpt(age=4))         # newest age wins
        man = st.publish_manifest("r-dead")
        assert man is not None and man["schema"] == "orphans-v1"
        assert man["replica_id"] == "r-dead"
        assert man["model_tag"] == "pm@1"
        [orphan] = man["orphans"]
        assert orphan["fold_key"] == "fk" and orphan["age"] == 4
        got = read_manifest(backend, "r-dead")
        assert got is not None and got["orphans"] == man["orphans"]
        assert clear_manifest(backend, "r-dead")
        assert read_manifest(backend, "r-dead") is None

    def test_empty_store_publishes_nothing(self, tmp_path):
        backend = FilesystemObjectStore(str(tmp_path / "shared"))
        st = CheckpointStore(str(tmp_path / "ck"), model_tag="pm@1",
                             registry=MetricsRegistry())
        st.backend = backend
        assert st.publish_manifest("r-idle") is None
        assert read_manifest(backend, "r-idle") is None

    def test_torn_or_alien_manifest_reads_as_none(self, tmp_path):
        backend = FilesystemObjectStore(str(tmp_path / "shared"))
        backend.put(manifest_key("rx"), b'{"schema": "orphans-v1"')
        assert read_manifest(backend, "rx") is None
        backend.put(manifest_key("ry"),
                    json.dumps({"schema": "other", "orphans": []})
                    .encode("utf-8"))
        assert read_manifest(backend, "ry") is None
        assert read_manifest(None, "rz") is None

    def test_publish_mirrors_checkpoints_to_backend(self, tmp_path):
        """The manifest alone is useless unless the checkpoint bytes are
        readable from the shared backend by a survivor with an EMPTY
        local disk tier."""
        backend = FilesystemObjectStore(str(tmp_path / "shared"))
        st = CheckpointStore(str(tmp_path / "ck_a"), model_tag="pm@1",
                             registry=MetricsRegistry())
        st.backend = backend
        st.put_row(_mk_ckpt(age=3))
        man = st.publish_manifest("rA")
        other = CheckpointStore(str(tmp_path / "ck_b"),
                                model_tag="pm@1",
                                registry=MetricsRegistry())
        other.backend = backend
        ck = other.latest(man["orphans"][0]["fold_key"])
        assert ck is not None and ck.age == 3
        assert np.array_equal(ck.seq, np.arange(8, dtype=np.int32))


# -- survivor-side adoption resume ------------------------------------


class TestAdoptionResume:
    def test_adopted_fold_resumes_byte_equal(self, tmp_path):
        """The acceptance choreography, in-process: victim A drains
        under a grace window it cannot fit (spill + manifest), survivor
        B pulls the checkpoint through the shared backend and resumes —
        coords byte-equal to an uninterrupted run, recycles lost <=
        checkpoint_every."""
        backend = FilesystemObjectStore(str(tmp_path / "shared"))
        # uninterrupted baseline
        stub_c = _PmStub()
        sc = _sched(stub_c, str(tmp_path / "spill_c"), num_recycles=8)
        with sc:
            rc = sc.submit(_req()).result(timeout=120)
        assert rc.ok and stub_c.steps() == 8

        # victim: slow steps, preempted mid-loop
        stub_a = _PmStub(step_sleep_s=0.05)
        sa = _sched(stub_a, str(tmp_path / "spill_a"), num_recycles=8)
        sa.checkpoint_store.backend = backend
        sa.start()
        try:
            ta = sa.submit(_req())
            assert _wait(lambda: stub_a.steps() >= 2)
            sa.drain(grace_s=0.3)
        finally:
            sa.stop()
        assert ta.result(timeout=30).status == "preempted"
        man = sa.checkpoint_store.publish_manifest("rA")
        assert man is not None and len(man["orphans"]) == 1
        orphan = man["orphans"][0]

        # survivor: empty disk tier, same shared backend
        stub_b = _PmStub()
        sb = _sched(stub_b, str(tmp_path / "spill_b"), num_recycles=8)
        sb.checkpoint_store.backend = backend
        ck = sb.checkpoint_store.latest(orphan["fold_key"])
        assert ck is not None and ck.age == orphan["age"]
        # checkpoint_every=1: the spill is at most one recycle behind
        assert stub_a.steps() - ck.age <= 1
        with sb:
            rb = sb.submit(FoldRequest(seq=np.asarray(ck.seq))) \
                .result(timeout=120)
        assert rb.ok
        st = sb.serve_stats()["resilience"]["checkpoint_spill"]
        assert st["spill_resumes"] == 1
        # resumed AT the checkpointed age, not refolded from zero
        assert stub_b.steps() == 8 - ck.age
        assert np.array_equal(rb.coords, rc.coords)
        assert np.array_equal(rb.confidence, rc.confidence)


# -- controller orphan adoption ---------------------------------------


class TestControllerAdoption:
    def test_sweep_death_assigns_to_live_survivor(self, tmp_path):
        mini = _MiniFleet()
        clk = [100.0]
        store = FilesystemObjectStore(str(tmp_path / "shared"))
        mreg = MetricsRegistry()
        try:
            r0 = mini.spawn()
            r1 = mini.spawn()
            ctrl = _controller(mini, clk, orphan_store=store,
                               registry=mreg)
            ctrl.reconcile()
            assert ctrl.registry.is_healthy(r1)
            payloads = []

            def adopt(payload):
                payloads.append(payload)
                return {"adopted": len(payload["orphans"])}

            mini.doors[r0].server.adopt_handler = adopt
            # r1 wedges: door dies, endpoint stays listed -> TTL sweep
            door = mini.doors.pop(r1)
            url = door.server.url
            door.__exit__()
            mini.extra_endpoints[r1] = url
            clk[0] += 6.0
            rec = ctrl.reconcile()
            assert rec["swept"] == [r1]
            # death detected but no manifest yet: adoption stays
            # pending and retries next tick (the replica spends its
            # grace window spilling before it publishes)
            assert rec["orphan_adoptions"] == []
            assert r1 in ctrl._pending_adoptions
            _put_manifest(store, r1,
                          [{"group": "g1", "fold_key": "fk1",
                            "age": 3, "model_tag": "v1"}])
            clk[0] += 1.0
            rec = ctrl.reconcile()
            [ad] = rec["orphan_adoptions"]
            assert ad["source"] == "sweep" and ad["survivor"] == r0
            assert ad["orphans"] == 1 and ad["adopted"] == 1
            assert payloads[0]["replica_id"] == r1
            assert payloads[0]["source"] == "sweep"
            assert payloads[0]["orphans"][0]["fold_key"] == "fk1"
            # manifest cleared (idempotent across ticks), pending done
            assert read_manifest(store, r1) is None
            assert r1 not in ctrl._pending_adoptions
            snap = ctrl.snapshot()["orphan_adoptions"]
            assert snap["adopted"] == 1
            assert snap["by_source"] == {"sweep": 1}
            assert "fleet_orphan_adoptions_total" in mreg.snapshot()
        finally:
            mini.stop()

    def test_notice_death_is_source_notice(self, tmp_path):
        mini = _MiniFleet()
        clk = [100.0]
        store = FilesystemObjectStore(str(tmp_path / "shared"))
        try:
            r0 = mini.spawn()
            r1 = mini.spawn()
            ctrl = _controller(mini, clk, orphan_store=store)
            ctrl.reconcile()
            payloads = []
            mini.doors[r0].server.adopt_handler = lambda p: (
                payloads.append(p) or {"adopted": len(p["orphans"])})
            # the replica announces its reclaim on /healthz (503 body)
            mini.doors[r1].scheduler.preempt_notice(30.0)
            clk[0] += 1.0
            ctrl.reconcile()
            assert r1 in ctrl._preempting_seen
            assert r1 in ctrl._pending_adoptions
            # it drains, publishes, and exits clean: endpoint gone
            mini.remove(r1)
            _put_manifest(store, r1,
                          [{"group": "g2", "fold_key": "fk2",
                            "age": 5, "model_tag": "v1"}])
            clk[0] += 1.0
            rec = ctrl.reconcile()
            [ad] = rec["orphan_adoptions"]
            assert ad["source"] == "notice" and ad["survivor"] == r0
            assert payloads[0]["source"] == "notice"
            assert ctrl.snapshot()["orphan_adoptions"]["by_source"] \
                == {"notice": 1}
        finally:
            mini.stop()

    def test_rejoin_cancels_pending_adoption(self):
        mini = _MiniFleet()
        clk = [100.0]
        try:
            r0 = mini.spawn()
            ctrl = _controller(mini, clk,
                               orphan_store=_NullStore())
            ctrl.reconcile()
            # a restart beat the controller to it: the rid is healthy
            # again, so its own boot discovery owns the checkpoints
            ctrl._pending_adoptions.add(r0)
            ctrl._preempting_seen[r0] = clk[0]
            clk[0] += 1.0
            rec = ctrl.reconcile()
            assert rec["orphan_adoptions"] == []
            assert r0 not in ctrl._pending_adoptions
            assert r0 not in ctrl._preempting_seen
        finally:
            mini.stop()

    def test_no_orphan_store_keeps_identity(self):
        mini = _MiniFleet()
        clk = [100.0]
        mreg = MetricsRegistry()
        try:
            mini.spawn()
            ctrl = _controller(mini, clk, registry=mreg)
            rec = ctrl.reconcile()
            assert "orphan_adoptions" not in rec
            assert "orphan_adoptions" not in ctrl.snapshot()
            assert "fleet_orphan_adoptions_total" not in mreg.snapshot()
        finally:
            mini.stop()


class _NullStore:
    """Empty ObjectStoreBackend: every manifest read misses."""

    def get(self, key):
        return None

    def put(self, key, data):
        pass

    def delete(self, key):
        pass


# -- fast failover on announced reclaim -------------------------------


class TestFastFailover:
    def test_healthz_503_carries_preempting_state(self):
        with _Door(replica_id="pz") as d:
            body = json.loads(urllib.request.urlopen(
                d.server.url + "/healthz", timeout=10).read())
            assert "preempting" not in body       # healthy identity pin
            d.scheduler.preempt_notice(30.0)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(d.server.url + "/healthz",
                                       timeout=10)
            assert ei.value.code == 503
            payload = json.loads(ei.value.read().decode("utf-8"))
            assert payload["preempting"] is True
            assert payload["replica"] == "pz"

    def test_fleet_client_marks_down_on_first_refusal(self):
        with _Door(replica_id="p0") as d0, _Door(replica_id="p1") as d1:
            d0.scheduler.preempt_notice(30.0)
            client = FleetClient([d0.server.url, d1.server.url],
                                 result_timeout_s=30.0)
            assert client.fold(_fold_req(0), hint=0).ok
            assert client.preempt_markdowns == 1
            assert client.snapshot()["preempt_markdowns"] == 1
            # the marked replica is SKIPPED now, not re-refused
            assert client.fold(_fold_req(1), hint=0).ok
            assert client.preempt_markdowns == 1

    def test_fleet_client_snapshot_identity_without_reclaim(self):
        with _Door(replica_id="p0") as d0:
            client = FleetClient([d0.server.url], result_timeout_s=30.0)
            assert client.fold(_fold_req(2), hint=0).ok
            snap = client.snapshot()
            assert "preempt_markdowns" not in snap
            assert "preempt_failovers" not in snap

    def test_peer_client_immediate_markdown(self):
        mreg = MetricsRegistry()
        reg = ReplicaRegistry(registry=mreg)
        reg.register("me")
        reg.register("p1")
        client = PeerCacheClient(reg, "me", metrics=mreg)

        class _Exc(Exception):
            def __init__(self, code, body):
                self.code = code
                self._b = body

            def read(self):
                return self._b

        assert client._note_preempting(
            "p1", _Exc(503, b'{"preempting": true}')) is True
        assert not reg.is_healthy("p1")
        assert client.preempt_markdowns == 1
        # anything else takes the normal strike count-up path
        assert client._note_preempting(
            "p1", _Exc(503, b'{"error": "draining"}')) is False
        assert client._note_preempting(
            "p1", _Exc(500, b'{"preempting": true}')) is False
        assert client._note_preempting("p1", _Exc(503, b"torn{")) is False
        assert client.preempt_markdowns == 1


# -- autoscaler suppression -------------------------------------------


@pytest.mark.quick
class TestAutoscalerSuppression:
    def _hot(self, rid, **kw):
        return ReplicaSignals(replica_id=rid, burn_rate=2.0,
                              idle_fraction=0.0, **kw)

    def test_burn_scale_up_suppressed_during_preemption(self):
        pol = ScalingPolicy(min_replicas=1, max_replicas=4,
                            cooldown_s=0.0)
        d = decide_scale(pol, [self._hot("a"),
                               self._hot("b", preempting=True,
                                         draining=True)], now=100.0)
        assert d.action == HOLD and "preemption" in d.reason

    def test_same_burn_without_notice_scales_up(self):
        pol = ScalingPolicy(min_replicas=1, max_replicas=4,
                            cooldown_s=0.0)
        d = decide_scale(pol, [self._hot("a"), self._hot("b")],
                         now=100.0)
        assert d.action == SCALE_UP

    def test_quorum_restore_beats_suppression(self):
        # the reclaimed member's REPLACEMENT is quorum restore's job —
        # suppression must never block it
        pol = ScalingPolicy(min_replicas=2, max_replicas=4)
        d = decide_scale(pol, [self._hot("a", preempting=True)],
                         now=100.0)
        assert d.action == SCALE_UP and "quorum" in d.reason


# -- feature-off identity pin -----------------------------------------


class TestOffIdentity:
    def test_no_notice_mints_nothing(self):
        reg = MetricsRegistry()
        s = _sched(_PmStub(), "", num_recycles=2, registry=reg)
        with s:
            assert s.submit(_req()).result(timeout=60).ok
        stats = s.serve_stats()
        assert "preemption" not in stats
        assert "preempting" not in s.health()
        names = set(reg.snapshot())
        assert "serve_preempt_notices_total" not in names
        assert "serve_preempt_drain_spills_total" not in names
        # no "preempted" status key leaks into the scrubbed stats
        assert '"preempted"' not in json.dumps(stats, default=str)


# -- multi-process chaos e2e (slow tier) ------------------------------


@pytest.mark.slow
class TestPreemptChaosE2E:
    """Notice + grace kill against real replica processes: 0 lost
    folds, 0 innocent casualties, the victim beats the hard kill with a
    clean exit, and (when loops were in flight) the controller assigns
    every orphan to a survivor. serve_smoke.sh phase 18 in miniature."""

    def test_preempt_grace_kill_zero_lost(self, tmp_path):
        fl = ProcFleet(3, str(tmp_path / "run"), model_tag="t@v1",
                       model={"dim": 16, "depth": 1, "msa_depth": 0},
                       num_recycles=48, preemption=True,
                       controller={"interval_s": 0.3,
                                   "heartbeat_timeout_s": 4.0,
                                   "probe_timeout_s": 2.0})
        with fl:
            victim = fl.replicas[2]
            assert victim.config.get("preempt_notice_path")
            client = FleetClient(
                [h.frontdoor_url for h in fl.replicas],
                result_timeout_s=240.0)
            results, lock = [], threading.Lock()

            def worker(seed):
                rng = np.random.default_rng(seed)
                req = FoldRequest(seq=rng.integers(
                    0, 20, size=24).astype(np.int32))
                r = client.fold(req, hint=seed % 3)
                with lock:
                    results.append(r)

            threads = [threading.Thread(target=worker, args=(s,))
                       for s in range(24)]
            for i, t in enumerate(threads):
                t.start()
                if i == 8:
                    fl.preempt(2, grace_s=4.0)
                time.sleep(0.05)
            for t in threads:
                t.join(timeout=300)
            # 0 lost folds, 0 innocent casualties
            assert len(results) == 24
            assert all(r.ok for r in results)
            # the grace-budgeted drain beat the hard kill: clean exit
            assert victim.proc.wait(30) == 0
            orphans = None
            with open(victim.log_path) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("preempted"):
                        orphans = int(rec.get("orphans", 0))
            assert orphans is not None          # the exit line printed
            if orphans:
                # every orphan adopted by controller assignment,
                # reconcile-tick-bounded (generous CI deadline)
                def adopted():
                    snap = fl.controller.snapshot() \
                        .get("orphan_adoptions") or {}
                    return snap.get("adopted", 0) >= orphans
                assert _wait(adopted, timeout_s=60.0, interval_s=0.5)
