"""Mesh-aware serving tests (ISSUE 7) on the virtual 8-device CPU
platform: per-bucket mesh policy + analytic HBM admission, the
slice allocator, the mesh-capable FoldExecutor (sharded == single-chip
numerics, ExecKey staleness fixes), and the scheduler's concurrent
disjoint-slice dispatch — plus the mesh_policy=None byte-identical
regression guard."""

import json
import threading
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_requests
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, DeviceSliceAllocator,
                                  FoldExecutor, FoldMemoryModel,
                                  FoldRequest, MeshPolicy, Scheduler,
                                  SchedulerConfig, ServeMetrics)
from alphafold2_tpu.serve.meshpolicy import (factor_chips, mesh_label,
                                             normalize_shape)

MSA_DEPTH = 3

multichip = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


@pytest.fixture(scope="module")
def model_and_params():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1)
    n = 16
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
        mask=jnp.ones((1, n), bool),
        msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
    return model, params


def _batch(bucket_len=16, batch=2, msa_depth=MSA_DEPTH, seed=0):
    rng = np.random.default_rng(seed)
    out = {"seq": jnp.asarray(
               rng.integers(0, 20, (batch, bucket_len)), jnp.int32),
           "mask": jnp.ones((batch, bucket_len), bool),
           "msa": None, "msa_mask": None}
    if msa_depth:
        out["msa"] = jnp.asarray(
            rng.integers(0, 20, (batch, msa_depth, bucket_len)),
            jnp.int32)
        out["msa_mask"] = jnp.ones((batch, msa_depth, bucket_len), bool)
    return out


@pytest.mark.quick
class TestMeshShapes:
    def test_factor_chips(self):
        assert factor_chips(1) == (1, 1)
        assert factor_chips(2) == (1, 2)
        assert factor_chips(4) == (2, 2)
        assert factor_chips(8) == (2, 4)
        with pytest.raises(ValueError):
            factor_chips(3)

    def test_normalize_and_label(self):
        assert normalize_shape(4) == (2, 2)
        assert normalize_shape((4, 2)) == (4, 2)
        assert mesh_label((2, 4)) == "2x4"


@pytest.mark.quick
class TestFoldMemoryModel:
    def test_monotone_in_length_and_sharding(self):
        mem = FoldMemoryModel(param_bytes=1 << 20, dim=64, heads=4)
        b16 = mem.fold_bytes(16, 2, 3)
        b64 = mem.fold_bytes(64, 2, 3)
        b256 = mem.fold_bytes(256, 2, 3)
        assert b16 < b64 < b256                   # O(L^2) dominates
        # sharding divides the activation terms, never below params
        assert mem.fold_bytes(256, 2, 3, chips=4) < b256
        assert mem.fold_bytes(256, 2, 3, chips=8) \
            >= mem.param_bytes

    def test_msa_term_shards_over_i_only(self):
        """The MSA track is sharded over the i axis only (msa_spec /
        fold_input_specs place nothing on j): a (1, 8) slice leaves the
        MSA replicated while (8, 1) divides it 8-fold — the footprint
        must price the actual shape, not the chip count."""
        mem = FoldMemoryModel(param_bytes=0, dim=64, heads=4)
        wide_j = mem.fold_bytes(256, 1, 512, shape=(1, 8))
        wide_i = mem.fold_bytes(256, 1, 512, shape=(8, 1))
        assert wide_j > wide_i
        # bare chip count prices the canonical squarest factorization
        assert mem.fold_bytes(256, 1, 512, chips=8) \
            == mem.fold_bytes(256, 1, 512, shape=(2, 4))

    def test_fits_boundary(self):
        mem = FoldMemoryModel(param_bytes=0, dim=32, heads=2,
                              hbm_bytes_per_device=10 << 20)
        assert mem.fits(16, 2, 3)
        assert not mem.fits(2048, 2, 3)
        # a bucket that misses single-chip can fit a bigger slice
        for L in (128, 256, 512):
            if not mem.fits(L, 2, 3, 1):
                assert mem.fold_bytes(L, 2, 3, 8) \
                    < mem.fold_bytes(L, 2, 3, 1)

    def test_from_model_reads_params(self, model_and_params):
        model, params = model_and_params
        mem = FoldMemoryModel.from_model(model, params, hbm_gb=16.0)
        n_params = sum(leaf.size for leaf in jax.tree.leaves(params))
        assert mem.param_bytes == n_params * 4
        assert mem.dim == 32 and mem.heads == 2


@pytest.mark.quick
class TestMeshPolicy:
    def test_shape_map_and_default(self):
        pol = MeshPolicy({32: 1, 512: 4}, devices=list(range(8)))
        assert pol.shape_for(32) == (1, 1)
        assert pol.shape_for(512) == (2, 2)
        assert pol.shape_for(64) == (1, 1)      # unmapped -> single chip
        assert pol.chips_for(512) == 4
        assert pol.snapshot()["policy"] == {"32": "1x1", "512": "2x2"}

    def test_clamps_to_device_pool(self):
        pol = MeshPolicy({512: 8}, devices=list(range(2)))
        assert pol.chips_for(512) == 2
        assert pol.snapshot()["clamped"] == {"512": "2x4"}
        # degenerate 1-device pool: everything single-chip, no crash
        pol1 = MeshPolicy({512: 8}, devices=list(range(1)))
        assert pol1.shape_for(512) == (1, 1)

    def test_from_model_picks_smallest_fitting_slice(self,
                                                     model_and_params):
        model, params = model_and_params
        pol = MeshPolicy.from_model(
            model, params, BucketPolicy((32, 64, 512)), max_batch=2,
            msa_depth=MSA_DEPTH, hbm_gb=0.01, devices=list(range(8)))
        mem = pol.memory
        # every assigned slice is the SMALLEST fitting power of two
        for edge in (32, 64, 512):
            chips = pol.chips_for(edge)
            if mem.fits(edge, 2, MSA_DEPTH, chips) and chips > 1:
                assert not mem.fits(edge, 2, MSA_DEPTH, chips // 2)
        # short buckets stay single-chip at this budget
        assert pol.chips_for(32) == 1

    def test_admits(self, model_and_params):
        model, params = model_and_params
        pol = MeshPolicy.from_model(
            model, params, BucketPolicy((32, 4096)), max_batch=2,
            msa_depth=MSA_DEPTH, hbm_gb=0.01, devices=list(range(8)))
        assert pol.admits(32, 2, MSA_DEPTH)
        assert not pol.admits(4096, 2, MSA_DEPTH)
        # no memory model -> admit everything
        assert MeshPolicy({}, devices=[0]).admits(4096, 2, MSA_DEPTH)


@pytest.mark.quick
class TestDeviceSliceAllocator:
    def test_aligned_disjoint_slices(self):
        alloc = DeviceSliceAllocator(list(range(8)))
        a = alloc.acquire((2, 2))
        b = alloc.acquire((2, 2))
        assert a.devices == [0, 1, 2, 3] and b.devices == [4, 5, 6, 7]
        assert alloc.acquire((1, 1)) is None     # pool exhausted
        assert not alloc.can_allocate((1, 1))
        alloc.release(a)
        c = alloc.acquire((1, 2))
        assert c.devices == [0, 1]               # aligned reuse
        assert alloc.busy_devices == 6

    def test_oversized_and_snapshot(self):
        alloc = DeviceSliceAllocator(list(range(2)))
        assert alloc.acquire((2, 2)) is None
        assert not alloc.can_allocate((2, 2))
        assert alloc.snapshot() == {"total_devices": 2,
                                    "busy_devices": 0}

    def test_blocking_acquire_wakes_on_release(self):
        alloc = DeviceSliceAllocator(list(range(2)))
        first = alloc.acquire((1, 2))
        got = []

        def waiter():
            got.append(alloc.acquire_blocking((1, 2), timeout_s=10))

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        alloc.release(first)
        t.join(timeout=10)
        assert got and got[0].devices == [0, 1]
        with pytest.raises(TimeoutError):
            alloc.acquire_blocking((1, 2), timeout_s=0.05)


@multichip
class TestExecutorMesh:
    def test_sharded_matches_single_chip(self, model_and_params):
        model, params = model_and_params
        ex = FoldExecutor(model, params, max_entries=16, model_tag="v1")
        batch = _batch()
        ref = ex.run(batch, 0)
        for devices, shape in ((jax.devices()[:2], (1, 2)),
                               (jax.devices()[:4], (2, 2))):
            got = ex.run(batch, 0, devices=devices, mesh_shape=shape)
            np.testing.assert_allclose(
                np.asarray(got.coords), np.asarray(ref.coords),
                atol=1e-3)
            np.testing.assert_allclose(
                np.asarray(got.confidence), np.asarray(ref.confidence),
                atol=1e-3)

    def test_single_device_slice_off_default_device(self,
                                                    model_and_params):
        model, params = model_and_params
        ex = FoldExecutor(model, params, max_entries=16)
        batch = _batch(seed=1)
        ref = ex.run(batch, 0)
        got = ex.run(batch, 0, devices=[jax.devices()[5]])
        np.testing.assert_allclose(np.asarray(got.coords),
                                   np.asarray(ref.coords), atol=1e-3)

    def test_exec_key_covers_mesh_shape_and_model_tag(
            self, model_and_params):
        model, params = model_and_params
        ex = FoldExecutor(model, params, max_entries=16, model_tag="v1")
        batch = _batch()
        k_single = ex.key_for(batch, 0)
        k_mesh = ex.key_for(batch, 0, mesh_shape=(2, 2))
        assert k_single[:4] == k_mesh[:4]
        assert k_single != k_mesh
        assert k_single[4] == (1, 1) and k_single[5] == "v1"

    def test_rollout_never_serves_stale_executable(self,
                                                   model_and_params):
        """ISSUE 7 satellite: a weight rollout (model_tag reassignment)
        must compile fresh, never reuse an executable minted under the
        previous tag — for the default path AND warmup."""
        model, params = model_and_params
        ex = FoldExecutor(model, params, max_entries=16, model_tag="v1")
        batch = _batch()
        ex.run(batch, 0)
        hits = ex.hits
        ex.run(batch, 0)
        assert ex.hits == hits + 1                 # same tag: cache hit
        ex.model_tag = "v2"
        misses = ex.misses
        ex.run(batch, 0)
        assert ex.misses == misses + 1             # rolled: fresh compile
        # warmup keys carry the tag too: legacy 4-tuples normalize onto
        # the CURRENT tag, so a rolled executor re-warms for real
        fresh = ex.warmup([(16, 2, MSA_DEPTH, 0)])
        assert fresh == 0                          # already compiled @v2
        ex.model_tag = "v3"
        assert ex.warmup([(16, 2, MSA_DEPTH, 0)]) == 1

    def test_scheduler_retag_propagates_to_executor(self,
                                                    model_and_params):
        model, params = model_and_params
        ex = FoldExecutor(model, params, model_tag="v1")
        sched = Scheduler(ex, BucketPolicy((16,)), model_tag="v1")
        sched.model_tag = "v1+rolled"              # what a rollout does
        assert ex.model_tag == "v1+rolled"
        # rebuild (watchdog path) carries the tag forward
        assert ex.rebuild().model_tag == "v1+rolled"


def _fake_fold_result(batch):
    b, n = batch["seq"].shape
    return SimpleNamespace(coords=np.zeros((b, n, 3), np.float32),
                           confidence=np.ones((b, n), np.float32))


class _BarrierExecutor:
    """Fake mesh-capable executor: run() blocks on a barrier, so the
    test only passes when two batches are IN FLIGHT simultaneously."""

    def __init__(self, parties):
        self.barrier = threading.Barrier(parties)
        self.calls = []

    def run(self, batch, num_recycles, trace=None, devices=None,
            mesh_shape=None):
        self.calls.append(tuple(getattr(d, "id", d) for d in devices))
        self.barrier.wait(timeout=30)
        return _fake_fold_result(batch)

    def stats(self):
        return {"hits": 0, "misses": 0, "evictions": 0}


@multichip
class TestSchedulerMesh:
    def _scheduler(self, model_and_params, mesh_policy, tracer=None,
                   registry=None, **kw):
        model, params = model_and_params
        ex = FoldExecutor(model, params, max_entries=32, model_tag="v1")
        cfg = SchedulerConfig(max_batch_size=2, max_wait_ms=10.0,
                              num_recycles=0, msa_depth=MSA_DEPTH)
        return Scheduler(
            ex, BucketPolicy((16, 32)), cfg,
            metrics=ServeMetrics(registry=registry or MetricsRegistry()),
            model_tag="v1", tracer=tracer,
            registry=registry or MetricsRegistry(),
            mesh_policy=mesh_policy, **kw)

    def test_mesh_e2e_outputs_match_single_chip(self, model_and_params,
                                                tmp_path):
        """Acceptance: a long-bucket fold sharded over a 2x2 slice
        matches the single-chip scheduler's coordinates/confidence
        within 1e-3, short folds stay 1-chip, and serve_stats()["mesh"]
        reports both shapes."""
        from alphafold2_tpu import obs

        reqs = synthetic_requests(jax.random.PRNGKey(1), num=6,
                                  lengths=(12, 24), msa_depth=MSA_DEPTH)
        tracer = obs.Tracer(jsonl_path=str(tmp_path / "traces.jsonl"))
        mesh_sched = self._scheduler(
            model_and_params, MeshPolicy({16: 1, 32: 4}), tracer=tracer)
        plain_sched = self._scheduler(model_and_params, None)

        def serve(sched):
            sched.warmup()
            out = {}
            with sched:
                for r in reqs:
                    t = sched.submit(FoldRequest(seq=r.seq, msa=r.msa))
                    out[r.request_id] = t.result(timeout=300)
            return out

        mesh_out = serve(mesh_sched)
        snap = mesh_sched.serve_stats()
        plain_out = serve(plain_sched)
        for rid, resp in mesh_out.items():
            assert resp.ok, resp.error
            ref = plain_out[rid]
            np.testing.assert_allclose(resp.coords, ref.coords,
                                       atol=1e-3)
            np.testing.assert_allclose(resp.confidence, ref.confidence,
                                       atol=1e-3)
        mesh = snap["mesh"]
        assert mesh["policy"] == {"16": "1x1", "32": "2x2"}
        assert mesh["folds"]["2x2"]["batches"] >= 1
        assert mesh["folds"]["1x1"]["batches"] >= 1
        assert mesh["allocator"]["busy_devices"] == 0    # all released
        # health carries occupancy for the fleet passthrough
        health_mesh = mesh_sched.health().get("mesh")
        assert health_mesh == {"total_devices": 8, "busy_devices": 0}
        # traces: every sharded fold has a shard span and a mesh-tagged
        # fold span; plain stats must NOT grow a mesh section
        tracer.close()
        fold_mesh, shard_spans = set(), 0
        with open(tmp_path / "traces.jsonl") as fh:
            for line in fh:
                for s in json.loads(line).get("spans", ()):
                    if s["name"] == "shard":
                        shard_spans += 1
                    if s["name"] == "fold":
                        fold_mesh.add(
                            (s.get("attrs") or {}).get("mesh"))
        assert shard_spans > 0
        assert {"1x1", "2x2"} <= fold_mesh
        assert "mesh" not in plain_sched.serve_stats()
        assert "mesh" not in plain_sched.health()

    def test_disjoint_slices_run_concurrently(self, model_and_params):
        """Two buckets on two 1-chip slices must be in flight AT THE
        SAME TIME: the barrier only releases when both executions have
        entered run() — a serial scheduler would deadlock (and fail via
        the barrier timeout)."""
        ex = _BarrierExecutor(parties=2)
        cfg = SchedulerConfig(max_batch_size=4, max_wait_ms=5.0,
                              num_recycles=0, msa_depth=MSA_DEPTH)
        sched = Scheduler(
            ex, BucketPolicy((16, 32)), cfg,
            metrics=ServeMetrics(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
            mesh_policy=MeshPolicy({16: 1, 32: 1},
                                   devices=jax.devices()[:2]))
        reqs = synthetic_requests(jax.random.PRNGKey(2), num=2,
                                  lengths=(12, 24), msa_depth=MSA_DEPTH)
        with sched:
            tickets = [sched.submit(FoldRequest(seq=r.seq, msa=r.msa))
                       for r in reqs]
            resps = [t.result(timeout=60) for t in tickets]
        assert [r.status for r in resps] == ["ok", "ok"]
        assert len(ex.calls) == 2
        assert set(ex.calls[0]).isdisjoint(ex.calls[1])   # disjoint chips

    def test_hbm_admission_guard_rejects_too_large(self,
                                                   model_and_params):
        """ISSUE 7 satellite: a fold whose analytic footprint exceeds
        the largest configured slice resolves "too_large" at submit —
        no queue, no executor, counter incremented."""
        model, params = model_and_params
        mem = FoldMemoryModel.from_model(model, params, hbm_gb=16.0)
        # budget between the 16-bucket and 32-bucket footprints
        lo = mem.fold_bytes(16, 2, MSA_DEPTH, 1)
        hi = mem.fold_bytes(32, 2, MSA_DEPTH, 1)
        assert lo < hi
        mem.hbm_bytes_per_device = (lo + hi) // 2
        reg = MetricsRegistry()
        pol = MeshPolicy({16: 1, 32: 1}, devices=jax.devices()[:1],
                         memory=mem)
        ex = FoldExecutor(model, params, max_entries=8, model_tag="v1")
        sched = Scheduler(
            ex, BucketPolicy((16, 32)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                            num_recycles=0, msa_depth=MSA_DEPTH),
            metrics=ServeMetrics(registry=reg), registry=reg,
            mesh_policy=pol)
        short, long_ = synthetic_requests(
            jax.random.PRNGKey(3), num=2, lengths=(12, 24),
            msa_depth=MSA_DEPTH)
        misses_before = ex.misses
        with sched:
            sched.warmup(msa_depth=MSA_DEPTH)
            ok = sched.submit(
                FoldRequest(seq=short.seq, msa=short.msa)).result(
                    timeout=300)
            too = sched.submit(
                FoldRequest(seq=long_.seq, msa=long_.msa)).result(
                    timeout=300)
        assert ok.ok
        assert too.status == "too_large"
        assert "admission guard" in too.error
        snap = sched.serve_stats()
        assert snap["too_large"] == 1
        counter = reg.snapshot()["serve_too_large_total"]
        assert sum(s["value"] for s in counter["samples"]) == 1
        # the rejected bucket never reached the executor — warmup skips
        # unadmitted buckets too, so exactly ONE signature compiled
        assert ex.misses == misses_before + 1

    def test_fleet_passthrough_carries_mesh(self, model_and_params):
        """ISSUE 7 fleet satellite: a mesh-aware replica's mesh section
        rides the existing fleet stats/health passthrough — no fleet
        wiring changed, the payloads come whole from the scheduler."""
        from alphafold2_tpu import fleet

        model, params = model_and_params
        fl = fleet.InProcessFleet(
            lambda: FoldExecutor(model, params, max_entries=8),
            BucketPolicy((16, 32)),
            SchedulerConfig(max_batch_size=2, msa_depth=MSA_DEPTH,
                            num_recycles=0),
            n_replicas=1, fleet=False, registry=MetricsRegistry(),
            mesh_policy_factory=lambda i: MeshPolicy(
                {16: 1, 32: 2}, devices=jax.devices()[:2]))
        rep = fl.replicas[0]
        assert rep.scheduler.health()["mesh"] == {
            "total_devices": 2, "busy_devices": 0}
        assert fl.stats()["replicas"]["r0"]["mesh"]["policy"] == \
            {"16": "1x1", "32": "1x2"}

    def test_too_large_guard_prices_request_msa_when_unpinned(
            self, model_and_params):
        """config.msa_depth=None must price each request's OWN MSA
        depth, not zero — a deep-MSA fold that cannot fit is rejected
        while the same sequence MSA-free is admitted."""
        model, params = model_and_params
        mem = FoldMemoryModel.from_model(model, params, hbm_gb=16.0)
        free = mem.fold_bytes(16, 2, 0, shape=(1, 1))
        deep = mem.fold_bytes(16, 2, 64, shape=(1, 1))
        assert free < deep
        mem.hbm_bytes_per_device = (free + deep) // 2
        ex = _BarrierExecutor(parties=1)
        sched = Scheduler(
            ex, BucketPolicy((16,)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                            num_recycles=0, msa_depth=None),
            metrics=ServeMetrics(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
            mesh_policy=MeshPolicy({16: 1}, devices=jax.devices()[:1],
                                   memory=mem))
        rng = np.random.default_rng(7)
        with sched:
            ok = sched.submit(FoldRequest(
                seq=rng.integers(0, 20, 12))).result(timeout=60)
            too = sched.submit(FoldRequest(
                seq=rng.integers(0, 20, 12),
                msa=rng.integers(0, 20, (64, 12)))).result(timeout=60)
        assert ok.status == "ok"
        assert too.status == "too_large"

    def test_too_large_still_serves_from_cache(self, model_and_params):
        """A fold this process can never execute may still have been
        computed elsewhere (peer with bigger slices, offline warm):
        a store hit serves it instead of rejecting — mirroring
        degraded mode's cache-hits-keep-serving contract."""
        from alphafold2_tpu.cache import FoldCache

        model, params = model_and_params
        mem = FoldMemoryModel.from_model(model, params, hbm_gb=16.0)
        mem.hbm_bytes_per_device = 1          # nothing fits
        ex = _BarrierExecutor(parties=1)
        sched = Scheduler(
            ex, BucketPolicy((16,)),
            SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                            num_recycles=0, msa_depth=0),
            metrics=ServeMetrics(registry=MetricsRegistry()),
            cache=FoldCache(registry=MetricsRegistry()),
            model_tag="v1", registry=MetricsRegistry(),
            mesh_policy=MeshPolicy({16: 1}, devices=jax.devices()[:1],
                                   memory=mem))
        rng = np.random.default_rng(8)
        req = FoldRequest(seq=rng.integers(0, 20, 12))
        with sched:
            first = sched.submit(req).result(timeout=60)
            assert first.status == "too_large"
            # the result arrives out of band (peer / offline warm)
            key = sched._cache_key_for(req)
            sched.cache.put(key, np.zeros((12, 3), np.float32),
                            np.ones((12,), np.float32))
            again = sched.submit(FoldRequest(seq=req.seq.copy())) \
                .result(timeout=60)
        assert again.status == "ok" and again.source == "cache"
        assert sched.serve_stats()["too_large"] == 1

    def test_mesh_autosizes_executor_lru(self, model_and_params):
        """Warmup compiles one executable per (bucket, aligned slice);
        the scheduler must grow the executor LRU to hold them or warmup
        evicts its own work."""
        model, params = model_and_params
        ex = FoldExecutor(model, params, max_entries=1)
        Scheduler(ex, BucketPolicy((16, 32)),
                  SchedulerConfig(max_batch_size=2, msa_depth=MSA_DEPTH),
                  metrics=ServeMetrics(registry=MetricsRegistry()),
                  registry=MetricsRegistry(),
                  mesh_policy=MeshPolicy({16: 1, 32: 4}))
        assert ex.max_entries == 8 + 2        # 8 1-chip + 2 4-chip slices

    def test_retag_prunes_param_placements(self, model_and_params):
        model, params = model_and_params
        ex = FoldExecutor(model, params, max_entries=8, model_tag="v1")
        ex.run(_batch(), 0, devices=[jax.devices()[3]])
        assert ex.stats()["placed_param_slices"] == 1
        ex.model_tag = "v2"                   # rollout: prune NOW, not
        assert ex.stats()["placed_param_slices"] == 0   # on next traffic

    def test_mesh_policy_none_serve_stats_byte_identical(
            self, model_and_params):
        """The off switch: mesh_policy=None must leave serve_stats()
        byte-identical to a scheduler that has never heard of meshes
        (scrubbed of wall-clock fields, same as the transport
        equivalence test)."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        def run_one(mesh_policy):
            sched = self._scheduler(model_and_params, mesh_policy)
            reqs = synthetic_requests(jax.random.PRNGKey(4), num=4,
                                      lengths=(12, 24),
                                      msa_depth=MSA_DEPTH)
            with sched:
                for r in reqs:
                    resp = sched.submit(
                        FoldRequest(seq=r.seq, msa=r.msa)).result(
                            timeout=300)
                    assert resp.ok
            return scrub(sched.serve_stats())

        a = run_one(None)
        b = run_one(None)
        assert json.dumps(a, sort_keys=True, default=str) \
            == json.dumps(b, sort_keys=True, default=str)
        assert "mesh" not in a
