"""Test harness: run everything on a virtual 8-device CPU platform so
multi-chip sharding is exercised without a TPU pod (SURVEY.md §4).

Two environment gotchas this file must handle (see
.claude/skills/verify/SKILL.md):
- the ambient env exports JAX_PLATFORMS=axon (the tunneled TPU); tests must
  OVERRIDE it, not setdefault, or every "CPU" test dispatches op-by-op over
  the TPU tunnel;
- the axon PJRT plugin is injected via PYTHONPATH=/root/.axon_site and its
  discovery dials the tunnel even under JAX_PLATFORMS=cpu — strip it from
  sys.path before jax initializes backends.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ.pop("PYTHONPATH", None)

import jax  # noqa: E402
import pytest  # noqa: E402

# Under a bare `python -m pytest tests` the axon sitecustomize hook has
# ALREADY imported jax at interpreter start (PYTHONPATH=/root/.axon_site),
# so jax's config captured JAX_PLATFORMS=axon before the env scrub above
# could matter — first backend use then dials the (possibly wedged) TPU
# tunnel and hangs with 0% CPU. Backends are not initialized yet at
# conftest time, so forcing the config value directly makes the bare
# invocation as safe as the scrubbed one (round-3 VERDICT weak #5).
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_default_matmul_precision", "float32")

# persistent compilation cache: the suite is compile-dominated (many tiny
# model configs); caching across runs cuts wall-clock dramatically.
# Configured via __graft_entry__._enable_compile_cache so the dir is
# NAMESPACED per platform/flags — a flat dir shared with the tunnel TPU
# clients produced entries whose deserialization segfaulted the CPU
# client mid-suite (r05). jax_platforms is already forced to "cpu" above,
# so the namespace key is correct here.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import __graft_entry__  # noqa: E402

__graft_entry__._enable_compile_cache()


def perturb_params(params, key, scale=0.05):
    """Add noise to every leaf — moves zero-init output projections off
    zero so backend/path-parity comparisons are not trivially 0 == 0."""
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(key, len(leaves))
    return treedef.unflatten(
        [l + scale * jax.random.normal(k, l.shape, l.dtype)
         for l, k in zip(leaves, keys)])


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "quick: fast smoke tier (one representative test per subsystem, "
        "~4-5 min on 1 CPU core): python -m pytest -m quick")
    config.addinivalue_line(
        "markers",
        "slow: heavyweight tier excluded from tier-1 (-m 'not slow'): "
        "multi-process fleets, real kill/partition chaos "
        "(tests/test_frontdoor.py's procfleet class, serve_smoke.sh "
        "phase 6 in miniature)")


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop in-process compiled executables after each test module: a
    monolithic 285-test process accumulated compiler state that
    segfaulted XLA:CPU compiling the pp train step ~57% in (r05, twice:
    once in cache deserialization, once in backend_compile_and_load).
    The persistent disk cache keeps recompiles cheap."""
    yield
    jax.clear_caches()
