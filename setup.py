from setuptools import find_packages, setup

setup(
    name="alphafold2-tpu",
    version="0.1.0",
    description=(
        "TPU-native (JAX/XLA/Pallas/pjit) protein-structure framework with "
        "the capabilities of lucidrains/alphafold2"
    ),
    packages=find_packages(exclude=("tests", "native", "scripts", "tools")),
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "orbax-checkpoint",
        "numpy",
    ],
    extras_require={
        "embeds": ["torch", "transformers"],
        "test": ["pytest"],
    },
)
