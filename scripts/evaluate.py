"""Fold-and-score entry point: the inference + eval-metrics stack
(SURVEY.md §3.5 — the reference's closest analog is two manual recycling
passes inside a test, test_attention.py:344-385; it has no eval CLI).

Folds a sequence with recycling (predict.fold) and, when a reference
PDB is given, reports CA RMSD / TM-score / GDT-TS / lDDT against it
(Kabsch-aligned where applicable). With --checkpoint, weights come from
an orbax checkpoint directory (scripts/train_*.py --config ... writes
one); otherwise random init — useful for pipeline smoke tests only.

Usage:
    python scripts/evaluate.py --pdb tests/data/1h22_head.pdb \
        [--config cfg.json] [--checkpoint DIR] [--recycles 3] \
        [--out pred.pdb] [--json metrics.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--pdb", required=True,
                    help="reference PDB: supplies the sequence and the "
                         "ground-truth CA trace to score against")
    ap.add_argument("--config", default=None)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--recycles", type=int, default=3)
    ap.add_argument("--out", default=None, help="write predicted CA PDB")
    ap.add_argument("--json", default=None, help="write metrics JSON")
    args = ap.parse_args(argv)

    from alphafold2_tpu.config import Experiment
    from alphafold2_tpu.core import geometry
    from alphafold2_tpu.data import native
    from alphafold2_tpu.predict import fold
    from alphafold2_tpu.train import CheckpointManager, TrainState

    if args.config:
        with open(args.config) as f:
            exp = Experiment.from_json(f.read())
    else:
        exp = Experiment()
        exp.model.dim, exp.model.depth = 64, 2
    exp.model.predict_coords = True

    with open(args.pdb) as f:
        seq_tok, coords14, atom_mask = native.parse_pdb(f.read())
    n = len(seq_tok)
    seq = jnp.asarray(seq_tok)[None]
    mask = jnp.asarray(atom_mask[:, 1])[None]          # CA resolved
    ca_true = jnp.asarray(coords14[:, 1])[None]        # (1, n, 3)

    from alphafold2_tpu.parallel import use_mesh

    model, tx, mesh = exp.build()
    with use_mesh(mesh):
        params = model.init(jax.random.PRNGKey(0), seq, msa=seq[:, None],
                            mask=mask, msa_mask=mask[:, None])
        if args.checkpoint:
            # the CONFIG's tx, not a fresh adam: the opt_state pytree
            # layout must match what the training script saved (e.g.
            # MultiSteps wrapping under grad_accum_every)
            state = TrainState.create(apply_fn=model.apply, params=params,
                                      tx=tx, rng=jax.random.PRNGKey(1))
            state = CheckpointManager(args.checkpoint).restore(state)
            params = state.params

        result = fold(model, params, seq, msa=seq[:, None], mask=mask,
                      msa_mask=mask[:, None], num_recycles=args.recycles)
    pred = result.coords

    metrics = {
        "n_residues": n,
        "recycles": args.recycles,
        "kabsch_rmsd": float(geometry.kabsch_rmsd(pred, ca_true,
                                                  mask=mask)[0]),
        "tm_score": float(geometry.kabsch_tm(pred, ca_true, mask=mask)[0]),
        "gdt_ts": float(geometry.kabsch_gdt(pred, ca_true, mask=mask)[0]),
        # lddt_ca is per-residue (b, n); report the masked mean
        "lddt": float((geometry.lddt_ca(ca_true, pred, mask=mask)[0] *
                       mask[0]).sum() / jnp.maximum(mask[0].sum(), 1)),
        # masked like the structural metrics: confidence at unresolved
        # (never-scored) positions must not skew the summary
        "mean_confidence": float((result.confidence[0] * mask[0]).sum() /
                                 jnp.maximum(mask[0].sum(), 1)),
        "checkpoint": args.checkpoint,
    }
    print(json.dumps(metrics))

    if args.json:
        with open(args.json, "w") as f:
            json.dump(metrics, f, indent=2)
    if args.out:
        from alphafold2_tpu.data.pdb_io import coords2pdb
        coords2pdb(np.asarray(seq[0]), np.asarray(pred[0]), name=args.out)
    return metrics


if __name__ == "__main__":
    main()
