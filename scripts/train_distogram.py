"""Distogram pretraining entry point.

The reference's train_pre.py (sidechainnet loader + Adam loop,
train_pre.py:37-96) as a config-driven jitted pipeline: synthetic batches
by default; a trrosetta-style on-disk dataset when --data points at a
directory of .a3m/.pdb pairs; a locally mounted sidechainnet pickle via
--scn (the reference's actual corpus, scn.load at train_pre.py:37-43);
or --pdb with one or more PDB files (real-structure demo without a
mounted corpus — e.g. tests/data/1h22_head.pdb).

Usage:
    python scripts/train_distogram.py [--config cfg.json] [--steps N]
        [--data DIR | --scn FILE.pkl | --pdb FILE...] [--mesh data,i,j]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from alphafold2_tpu.config import Experiment
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.parallel import use_mesh
from alphafold2_tpu.train import CheckpointManager, TrainState, fit
from alphafold2_tpu.utils import MetricsLogger, StepTimer


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--data", default=None)
    ap.add_argument("--scn", default=None,
                    help="local sidechainnet pickle (train_pre.py corpus)")
    ap.add_argument("--pdb", nargs="+", default=None,
                    help="PDB file(s) as a real-structure demo corpus")
    ap.add_argument("--mesh", default=None,
                    help="data,i,j or pipe,data,i,j")
    ap.add_argument("--log", default=None, help="metrics JSONL path")
    args = ap.parse_args(argv)

    if args.config:
        with open(args.config) as f:
            exp = Experiment.from_json(f.read())
    else:
        exp = Experiment()
        exp.model.dim, exp.model.depth = 128, 2
    if args.steps is not None:
        exp.train.num_steps = args.steps
    if args.data is not None:
        exp.data.root = args.data
    if args.mesh is not None:
        vals = [int(v) for v in args.mesh.split(",")]
        if len(vals) == 3:
            vals = [1] + vals   # full override: no pipe unless asked
        exp.mesh.pipe, exp.mesh.data, exp.mesh.i, exp.mesh.j = vals

    model, tx, mesh = exp.build()

    if args.scn or args.pdb:
        from alphafold2_tpu.data.sidechainnet import (SidechainnetDataModule,
                                                      corpus_from_pdb)
        source = args.scn if args.scn else corpus_from_pdb(args.pdb)
        dm = SidechainnetDataModule(source, crop_len=exp.data.crop_len,
                                    batch_size=exp.data.batch_size,
                                    max_msa_rows=exp.data.msa_depth)
        batches = dm.train_batches()
    elif exp.data.root:
        from alphafold2_tpu.data.trrosetta import TrRosettaDataModule
        dm = TrRosettaDataModule(exp.data.root, crop_len=exp.data.crop_len,
                                 batch_size=exp.data.batch_size,
                                 max_msa_rows=exp.data.msa_depth)
        batches = dm.train_batches()
    else:
        def synthetic_stream():
            i = 0
            while True:
                yield synthetic_batch(
                    jax.random.PRNGKey(i), batch=exp.data.batch_size,
                    seq_len=exp.data.crop_len,
                    msa_depth=exp.data.msa_depth)
                i += 1
        batches = synthetic_stream()

    first = next(batches)
    rng = jax.random.PRNGKey(exp.train.seed)

    with use_mesh(mesh):
        params = model.init(
            {"params": rng, "mlm": jax.random.fold_in(rng, 1)},
            first["seq"], msa=first.get("msa"), mask=first.get("mask"),
            msa_mask=first.get("msa_mask"), train=True)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=tx, rng=jax.random.fold_in(rng, 2))
        if mesh is not None:
            # TP specs for the projection kernels, ZeRO for the rest —
            # the same placement the multichip dryrun validates
            from alphafold2_tpu.parallel import shard_pytree_tp_zero
            state = shard_pytree_tp_zero(state, mesh)

        timer = StepTimer()
        logger = MetricsLogger(args.log)
        state, history = fit(model, state, batches, exp.train.num_steps,
                             log_every=exp.train.log_every, logger=logger,
                             step_timer=timer)

    print("step time:", timer.summary())
    if exp.train.checkpoint_dir:
        CheckpointManager(exp.train.checkpoint_dir).save(state)
    return history


if __name__ == "__main__":
    main()
