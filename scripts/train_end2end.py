"""End-to-end coordinate training entry point.

The reference's train_end2end.py intent (ESM-embedded inputs ->
predict_coords model -> Kabsch-RMSD + distogram-dispersion loss,
train_end2end.py:99-166 — stale/broken as written there, SURVEY.md §2.6)
as a runnable config-driven pipeline. The coordinate loss, confidence
regression, and MLM objective are wired through `train.compute_loss`.

Usage mirrors scripts/train_distogram.py; adds --structure-module
{ipa,egnn,en,se3} and --recycle N (outer recycling iterations, reference
test_attention.py:344-385 pattern).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from alphafold2_tpu.config import Experiment
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.parallel import use_mesh
from alphafold2_tpu.train import CheckpointManager, TrainState, fit
from alphafold2_tpu.utils import MetricsLogger, StepTimer


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--data", default=None)
    ap.add_argument("--mesh", default=None)
    ap.add_argument("--structure-module", default=None,
                    choices=["ipa", "egnn", "en", "se3"])
    ap.add_argument("--refinement-iters", type=int, default=None)
    ap.add_argument("--refinement", default=None,
                    choices=["residue", "egnn-atom"],
                    help="what --refinement-iters refines: the CA trace "
                         "(residue) or the 14-atom covalent graph "
                         "(egnn-atom, the notebook's atom-level mode)")
    ap.add_argument("--reversible", action="store_const", const=True,
                    default=None)
    ap.add_argument("--log", default=None)
    args = ap.parse_args(argv)

    if args.config:
        with open(args.config) as f:
            exp = Experiment.from_json(f.read())
    else:
        exp = Experiment()
        exp.model.dim, exp.model.depth = 128, 2
        exp.data.crop_len = 64
    exp.model.predict_coords = True
    # CLI flags override the config file only when explicitly passed
    if args.structure_module is not None:
        exp.model.structure_module_type = args.structure_module
    if args.refinement_iters is not None:
        exp.model.structure_module_refinement_iters = args.refinement_iters
    if args.refinement is not None:
        exp.model.structure_module_refinement = args.refinement
    if args.reversible is not None:
        exp.model.reversible = args.reversible
    if args.steps is not None:
        exp.train.num_steps = args.steps
    if args.data is not None:
        exp.data.root = args.data
    if args.mesh is not None:
        d, i, j = (int(v) for v in args.mesh.split(","))
        exp.mesh.data, exp.mesh.i, exp.mesh.j = d, i, j

    model, tx, mesh = exp.build()

    if exp.data.root:
        from alphafold2_tpu.data.trrosetta import TrRosettaDataModule
        dm = TrRosettaDataModule(exp.data.root, crop_len=exp.data.crop_len,
                                 batch_size=exp.data.batch_size,
                                 max_msa_rows=exp.data.msa_depth)
        batches = dm.train_batches()
    else:
        def synthetic_stream():
            i = 0
            while True:
                yield synthetic_batch(
                    jax.random.PRNGKey(i), batch=exp.data.batch_size,
                    seq_len=exp.data.crop_len,
                    msa_depth=exp.data.msa_depth, with_coords=True)
                i += 1
        batches = synthetic_stream()

    first = next(batches)
    rng = jax.random.PRNGKey(exp.train.seed)

    with use_mesh(mesh):
        params = model.init(
            {"params": rng, "mlm": jax.random.fold_in(rng, 1)},
            first["seq"], msa=first.get("msa"), mask=first.get("mask"),
            msa_mask=first.get("msa_mask"), train=True)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=tx, rng=jax.random.fold_in(rng, 2))
        if mesh is not None:
            # TP specs for the projection kernels, ZeRO for the rest —
            # the same placement the multichip dryrun validates
            from alphafold2_tpu.parallel import shard_pytree_tp_zero
            state = shard_pytree_tp_zero(state, mesh)

        timer = StepTimer()
        logger = MetricsLogger(args.log)
        state, history = fit(model, state, batches, exp.train.num_steps,
                             log_every=exp.train.log_every, logger=logger,
                             step_timer=timer)

    print("step time:", timer.summary())
    if exp.train.checkpoint_dir:
        CheckpointManager(exp.train.checkpoint_dir).save(state)
    return history


if __name__ == "__main__":
    main()
