"""Runnable multi-chip demo: every parallelism family on one model.

Works anywhere — on a machine with N real TPU chips it uses them; on a
laptop/CI it builds 8 virtual CPU devices. Run from the repo root:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/multichip.py

Shows, in one script:
  1. DP x 2-D pair sharding + ring attention + TP/ZeRO state placement
     (mesh (data=2, i=2, j=2)) — one training step;
  2. GPipe pipeline parallelism of the trunk (mesh (pipe=2, data=2)) —
     one training step with the SAME params tree (checkpoints move
     freely between the scanned and pipelined trunks).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "XLA_FLAGS" not in os.environ and "TPU_NAME" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import jax
import jax.numpy as jnp

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.parallel import (make_mesh, shard_pytree_tp_zero,
                                     use_mesh)
from alphafold2_tpu.train import (TrainState, adam, make_train_step,
                                  shard_batch)


def one_step(model, mesh, batch, tag):
    with use_mesh(mesh):
        params = model.init(jax.random.PRNGKey(1), batch["seq"],
                            msa=batch["msa"], mask=batch["mask"],
                            msa_mask=batch["msa_mask"])
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=adam(3e-4), rng=jax.random.PRNGKey(2))
        state = shard_pytree_tp_zero(state, mesh)
        step = jax.jit(make_train_step(model), donate_argnums=(0,))
        state, metrics = step(state, shard_batch(batch, mesh))
        jax.block_until_ready(metrics["loss"])
    print(f"[{tag}] mesh={dict(mesh.shape)} "
          f"loss={float(metrics['loss']):.4f}")
    return params


def main():
    n = len(jax.devices())
    assert n >= 8, f"want 8 devices for the demo, have {n}"
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=4, seq_len=16,
                            msa_depth=3, with_coords=True)

    # 1) dp x 2-D pair sharding, ring attention, TP + ZeRO placement
    mesh = make_mesh(2, 2, 2)
    model = Alphafold2(dim=32, depth=2, heads=4, dim_head=16,
                       predict_coords=True, structure_module_depth=2,
                       dtype=jnp.bfloat16, ring_attention=True)
    one_step(model, mesh, batch, "dp x sp(ring) x tp x zero")

    # 2) GPipe trunk: same architecture, pipe mesh axis
    mesh_pp = make_mesh(2, 2, 1, pipe=2)
    model_pp = Alphafold2(dim=32, depth=2, heads=4, dim_head=16,
                          predict_coords=True, structure_module_depth=2,
                          dtype=jnp.bfloat16, pipeline_stages=2)
    one_step(model_pp, mesh_pp, batch, "pp(GPipe) x dp")


if __name__ == "__main__":
    main()
