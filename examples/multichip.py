"""Runnable multi-chip demo: every parallelism family on one model.

Works anywhere — on a machine with N real TPU chips it uses them; on a
laptop/CI it builds 8 virtual CPU devices. Run from the repo root:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/multichip.py

Shows, in one script:
  1. DP x 2-D pair sharding + ring attention + TP/ZeRO state placement
     (mesh (data=2, i=2, j=2)) — one training step;
  2. GPipe pipeline parallelism of the trunk (mesh (pipe=2, data=2)) —
     one training step with the SAME params tree (checkpoints move
     freely between the scanned and pipelined trunks).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "XLA_FLAGS" not in os.environ and "TPU_NAME" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import jax
import jax.numpy as jnp

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.parallel import (make_mesh, shard_pytree_tp_zero,
                                     use_mesh)
from alphafold2_tpu.train import (TrainState, adam, make_train_step,
                                  shard_batch)


def one_step(model, mesh, batch, tag, params):
    with use_mesh(mesh):
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=adam(3e-4), rng=jax.random.PRNGKey(2))
        state = shard_pytree_tp_zero(state, mesh)
        # no donate_argnums here: the demo reuses `params` across both
        # runs, and donation would delete buffers the second run aliases
        # (in training loops, donate the state — train/loop.py does)
        step = jax.jit(make_train_step(model))
        state, metrics = step(state, shard_batch(batch, mesh))
        jax.block_until_ready(metrics["loss"])
    print(f"[{tag}] mesh={dict(mesh.shape)} "
          f"loss={float(metrics['loss']):.4f}")


def main():
    devices = jax.devices()[:8]
    assert len(devices) >= 8, \
        f"want 8 devices for the demo, have {len(devices)}"
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=4, seq_len=16,
                            msa_depth=3, with_coords=True)
    kw = dict(dim=32, depth=2, heads=4, dim_head=16, predict_coords=True,
              structure_module_depth=2, dtype=jnp.bfloat16)

    # ONE params tree serves both runs below: the pipelined trunk
    # regroups the same scan-stacked params, so checkpoints move freely
    model = Alphafold2(**kw, ring_attention=True)
    params = model.init(jax.random.PRNGKey(1), batch["seq"],
                        msa=batch["msa"], mask=batch["mask"],
                        msa_mask=batch["msa_mask"])

    # 1) dp x 2-D pair sharding, ring attention, TP + ZeRO placement
    mesh = make_mesh(2, 2, 2, devices=devices)
    one_step(model, mesh, batch, "dp x sp(ring) x tp x zero", params)

    # 2) GPipe trunk: same architecture and THE SAME params, pipe axis
    mesh_pp = make_mesh(2, 2, 1, pipe=2, devices=devices)
    model_pp = Alphafold2(**kw, pipeline_stages=2)
    one_step(model_pp, mesh_pp, batch, "pp(GPipe) x dp", params)


if __name__ == "__main__":
    main()
