"""1H22 memorization demo: prove the structure path LEARNS (VERDICT r4 #3).

Round-4's artifact trained at crop 64 / 0 recycles but scored at 72 res /
3 recycles — a protocol mismatch that left eval RMSD at 8.3 A, a whisker
above random init. This runner aligns the protocols: train on the FULL
72-residue 1H22 fixture (tests/data/1h22_head.pdb, the reference
notebooks' own validation protein, notebooks/data/1h22_protein.pdb) at
0 recycles, and score the SAME configuration (plus a 3-recycle contrast
row). An overfit fixture must reach crystal-memorization accuracy —
target Kabsch RMSD < 2 A, TM > 0.8 — or the structure path doesn't train.

Also reports confidence calibration: Pearson correlation and MAE between
the per-residue predicted lDDT (confidence head, trained by
train/losses.lddt_confidence_loss) and the realized per-residue lDDT of
the final prediction.

Usage: python examples/train_1h22.py [--steps 3000] [--out-dir examples]
Writes: examples/ckpt_1h22_full/ (orbax), examples/eval_1h22_metrics.json,
        examples/train_1h22_full_log.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

PDB = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "data", "1h22_head.pdb")


def _metrics(geometry, pred, ca_true, mask, confidence):
    per_res_lddt = geometry.lddt_ca(ca_true, pred, mask=mask)[0]
    m = np.asarray(mask[0], bool)
    conf = np.asarray(confidence[0])[m]
    real = np.asarray(per_res_lddt)[m]
    if conf.std() > 1e-6 and real.std() > 1e-6:
        pearson = float(np.corrcoef(conf, real)[0, 1])
    else:  # memorized fixture: both near-constant; correlation undefined
        pearson = None
    return {
        "kabsch_rmsd": float(geometry.kabsch_rmsd(pred, ca_true,
                                                  mask=mask)[0]),
        "tm_score": float(geometry.kabsch_tm(pred, ca_true, mask=mask)[0]),
        "gdt_ts": float(geometry.kabsch_gdt(pred, ca_true, mask=mask)[0]),
        "lddt": float(real.mean()),
        "mean_confidence": float(conf.mean()),
        "confidence_lddt_pearson": pearson,
        "confidence_lddt_mae": float(np.abs(conf - real).mean()),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3000)
    ap.add_argument("--log-every", type=int, default=50)
    ap.add_argument("--eval-every", type=int, default=250)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--target-rmsd", type=float, default=1.0,
                    help="early-stop once the protocol-matched eval RMSD "
                         "(at --train-recycles recycles) drops below this")
    ap.add_argument("--train-recycles", type=int, default=0,
                    help=">0: train with sampled recycling "
                         "(train.make_recycled_train_step) so eval at "
                         "recycles<=N is a TRAINED configuration")
    ap.add_argument("--out-dir", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__))))
    args = ap.parse_args(argv)

    from alphafold2_tpu import Alphafold2
    from alphafold2_tpu.core import geometry
    from alphafold2_tpu.data import native
    from alphafold2_tpu.predict import fold
    from alphafold2_tpu.train import (CheckpointManager, TrainState, adam,
                                      make_recycled_train_step,
                                      make_train_step)

    with open(PDB) as f:
        seq_tok, coords14, atom_mask = native.parse_pdb(f.read())
    n = len(seq_tok)
    seq = jnp.asarray(seq_tok)[None]
    mask = jnp.asarray(atom_mask[:, 1])[None]       # CA resolved
    ca_true = jnp.asarray(coords14[:, 1])[None]     # (1, n, 3)

    # same architecture as round-4's demo (examples/eval_1h22.json), but
    # float32 (CPU host: XLA:CPU emulates bf16) and FULL-length training
    model = Alphafold2(dim=64, depth=2, heads=4, dim_head=16,
                       predict_coords=True, structure_module_depth=2,
                       dtype=jnp.float32)
    batch = {"seq": seq, "msa": seq[:, None], "mask": mask,
             "msa_mask": mask[:, None], "coords": ca_true}

    params = model.init(
        {"params": jax.random.PRNGKey(0), "mlm": jax.random.PRNGKey(1)},
        seq, msa=batch["msa"], mask=mask, msa_mask=batch["msa_mask"],
        train=True)
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=adam(args.lr), rng=jax.random.PRNGKey(2))
    step_fn = make_recycled_train_step(model, args.train_recycles) \
        if args.train_recycles > 0 else make_train_step(model)
    step = jax.jit(step_fn, donate_argnums=(0,))

    import functools
    eval_recycles = args.train_recycles  # 0 -> protocol-aligned @0rec
    run_fold = jax.jit(functools.partial(fold, model,
                                         num_recycles=eval_recycles))

    log_path = os.path.join(args.out_dir, "train_1h22_full_log.jsonl")
    ckpt_dir = os.path.join(args.out_dir, "ckpt_1h22_full")
    t0 = time.time()
    best = None
    with open(log_path, "w") as log:
        for i in range(args.steps):
            state, metrics = step(state, batch)
            if i % args.log_every == 0 or i == args.steps - 1:
                row = {k: round(float(v), 4) for k, v in metrics.items()}
                row["step"] = i
                row["elapsed_s"] = round(time.time() - t0, 1)
                log.write(json.dumps(row) + "\n")
                log.flush()
                print(row, flush=True)
            if (i and i % args.eval_every == 0) or i == args.steps - 1:
                res = run_fold(state.params, seq, msa=batch["msa"],
                               mask=mask, msa_mask=batch["msa_mask"])
                rmsd = float(geometry.kabsch_rmsd(res.coords, ca_true,
                                                  mask=mask)[0])
                print({"step": i, "eval_rmsd": round(rmsd, 3)},
                      flush=True)
                log.write(json.dumps({"step": i,
                                      "eval_rmsd": round(rmsd, 3)})
                          + "\n")
                log.flush()
                best = rmsd if best is None else min(best, rmsd)
                if rmsd < args.target_rmsd:
                    print(f"early stop at step {i}: rmsd {rmsd:.3f}")
                    break

    CheckpointManager(ckpt_dir).save(state)

    # ---- final scoring: protocol-matched headline + the other row
    res0 = run_fold(state.params, seq, msa=batch["msa"], mask=mask,
                    msa_mask=batch["msa_mask"])
    other_recycles = 3 if eval_recycles == 0 else 0
    run_fold3 = jax.jit(functools.partial(fold, model,
                                          num_recycles=other_recycles))
    res3 = run_fold3(state.params, seq, msa=batch["msa"], mask=mask,
                     msa_mask=batch["msa_mask"])

    # random-init contrast, same fold path
    rnd_params = model.init(
        {"params": jax.random.PRNGKey(42), "mlm": jax.random.PRNGKey(43)},
        seq, msa=batch["msa"], mask=mask, msa_mask=batch["msa_mask"],
        train=True)
    res_rnd = run_fold(rnd_params, seq, msa=batch["msa"], mask=mask,
                       msa_mask=batch["msa_mask"])

    out = {
        "n_residues": n,
        "protocol": ("train full-length with sampled recycling 0..%d; "
                     "headline eval @%d recycles (matched)" %
                     (args.train_recycles, args.train_recycles))
        if args.train_recycles else
        "train full-length @0 recycles; headline eval @0 recycles "
        "(matched); recycles_3 row is the UNtrained-recycling contrast",
        "train_steps": int(state.step),
        "headline": _metrics(geometry, res0.coords, ca_true, mask,
                             res0.confidence),
        ("recycles_3" if eval_recycles == 0 else "recycles_0"):
            _metrics(geometry, res3.coords, ca_true, mask,
                     res3.confidence),
        "random_init_baseline": _metrics(geometry, res_rnd.coords, ca_true,
                                         mask, res_rnd.confidence),
        "checkpoint": ckpt_dir,
        "log": log_path,
        "train_recycles": args.train_recycles,
        "config": {"dim": 64, "depth": 2, "heads": 4, "dim_head": 16,
                   "structure_module_depth": 2, "dtype": "float32",
                   "lr": args.lr, "full_length": n, "msa_depth": 1},
    }
    path = os.path.join(args.out_dir, "eval_1h22_metrics.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    return out


if __name__ == "__main__":
    main()
