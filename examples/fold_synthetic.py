"""Fold a (synthetic) sequence end to end and write a PDB.

The runnable equivalent of the reference's notebook decode demos
(notebooks/*.ipynb): trunk forward -> recycling -> structure module ->
confidence -> PDB file. Swap `synthetic_batch` for your own featurized
sequence/MSA to fold real proteins.

  python examples/fold_synthetic.py [out.pdb]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.predict import fold_and_write

out_path = sys.argv[1] if len(sys.argv) > 1 else "folded.pdb"

model = Alphafold2(dim=64, depth=2, heads=4, dim_head=16,
                   predict_coords=True, structure_module_depth=2,
                   dtype=jnp.bfloat16)
batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=48,
                        msa_depth=4, with_coords=False)
params = model.init(jax.random.PRNGKey(1), batch["seq"], msa=batch["msa"],
                    mask=batch["mask"], msa_mask=batch["msa_mask"])

paths = fold_and_write(model, params, batch["seq"], out_path,
                       msa=batch["msa"], mask=batch["mask"],
                       msa_mask=batch["msa_mask"], num_recycles=3)
print(f"wrote {paths[0]}")
