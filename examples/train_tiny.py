"""Minimal end-to-end training loop on synthetic data.

The runnable equivalent of the reference's train_pre.py at toy scale:
jitted train step (distogram + MLM losses), warmup+cosine schedule,
non-finite-step guard, checkpointing. Multi-chip: wrap in
`use_mesh(make_mesh(...))` and shard with `shard_pytree_tp_zero` /
`shard_batch` exactly as __graft_entry__._dryrun_impl does.

  python examples/train_tiny.py [steps]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.train import TrainState, adam, make_train_step
from alphafold2_tpu.train.guard import guarded_train_step

steps = int(sys.argv[1]) if len(sys.argv) > 1 else 20

model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16)
batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=16,
                        msa_depth=3, with_coords=True)
params = model.init(
    {"params": jax.random.PRNGKey(1), "mlm": jax.random.PRNGKey(2)},
    batch["seq"], msa=batch["msa"], mask=batch["mask"],
    msa_mask=batch["msa_mask"], train=True)
state = TrainState.create(
    apply_fn=model.apply, params=params,
    tx=adam(1e-3, warmup_steps=5, decay_steps=steps),
    rng=jax.random.PRNGKey(3))

step = jax.jit(guarded_train_step(make_train_step(model)))
for i in range(steps):
    state, metrics = step(state, batch)
    if i % 5 == 0 or i == steps - 1:
        print(f"step {i}: loss={float(metrics['loss']):.4f} "
              f"skipped={int(metrics['skipped'])}")
