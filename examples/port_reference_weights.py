"""Port a torch reference checkpoint into this framework and verify it.

Usage with a reference-trained state_dict (saved via torch.save):

  python examples/port_reference_weights.py ckpt.pt

With no argument, builds a fresh reference-shaped model in torch,
ports its random weights, and checks distogram parity — the same path
tests/test_parity.py::TestWholeModelParity exercises.
"""

import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))
sys.path.insert(0, "/root/reference")


def main():
    import torch
    import _reference_stubs  # noqa: F401  (fills the reference's deps)
    from alphafold2_pytorch.alphafold2 import Alphafold2 as TorchAF2
    from port_weights import port_alphafold2

    import jax
    import jax.numpy as jnp
    from alphafold2_tpu import Alphafold2

    kw = dict(dim=32, depth=1, heads=2, dim_head=16)
    torch_model = TorchAF2(**kw).eval()
    if len(sys.argv) > 1:
        torch_model.load_state_dict(torch.load(sys.argv[1],
                                               map_location="cpu"))

    # outer_mean_reference_scale: bit-match the reference's OuterMean
    # normalization for ported checkpoints (see PARITY.md)
    flax_model = Alphafold2(**kw, outer_mean_reference_scale=True)
    rng = np.random.default_rng(0)
    seq = rng.integers(0, 20, (1, 16))
    msa = rng.integers(0, 20, (1, 3, 16))
    template = flax_model.init(
        jax.random.PRNGKey(0), jnp.asarray(seq), msa=jnp.asarray(msa))
    params, unported = port_alphafold2(torch_model, template)
    print("unported (framework-only) subtrees:", unported)

    with torch.no_grad():
        ref = torch_model(seq=torch.as_tensor(seq),
                          msa=torch.as_tensor(msa)).distance.numpy()
    ours = np.asarray(flax_model.apply(params, jnp.asarray(seq),
                                       msa=jnp.asarray(msa)).distance)
    err = float(np.abs(ref - ours).max())
    print(f"ported; max distogram deviation vs torch: {err:.2e}")
    assert err < 1e-3


if __name__ == "__main__":
    main()
