"""Benchmark: Evoformer training-step time @ 256-res crop (BASELINE.json
metric), run on whatever jax.devices() provides (the real TPU chip under the
driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}

`vs_baseline` is the speedup ratio vs the reference implementation's
matched-config training step (torch, measured on this host by
tools/measure_reference_baseline.py into tools/reference_baseline.json —
the reference publishes no numbers of its own, see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_DONE = threading.Event()

DIM = int(os.environ.get("BENCH_DIM", 256))
DEPTH = int(os.environ.get("BENCH_DEPTH", 2))
L = int(os.environ.get("BENCH_LEN", 256))
MSA, B = 5, 1
WARMUP = max(1, int(os.environ.get("BENCH_WARMUP", 2)))
ITERS = max(1, int(os.environ.get("BENCH_ITERS", 10)))

METRIC = (f"evoformer_distogram_train_step@{L}res(dim{DIM},"
          f"depth{DEPTH},msa{MSA},b{B})")


def _watchdog(seconds: int):
    """If the TPU tunnel is wedged, fail loudly with a JSON line instead
    of hanging the driver. A daemon thread (not SIGALRM): the hang sits
    inside a blocking C call during jax plugin discovery, so Python-level
    signal handlers would never run."""

    def waiter():
        if not _DONE.wait(seconds):
            print(json.dumps({
                "metric": METRIC,
                "value": None, "unit": "ms", "vs_baseline": None,
                "error": f"bench timed out after {seconds}s "
                         "(device backend unreachable?)"}), flush=True)
            os._exit(2)

    threading.Thread(target=waiter, daemon=True).start()


_watchdog(int(os.environ.get("BENCH_TIMEOUT_S", 1500)))


# If the default platform (the tunneled TPU) is unreachable, fall back to
# CPU and say so in the output instead of burning the watchdog budget —
# a labeled CPU number beats a null (BENCH_r01.json was null for exactly
# this reason). The probe is two-stage and sized to THIS bench's workload:
# stage 1 is a cheap tiny-op probe; stage 2 re-runs bench.py itself in
# compile-only mode (BENCH_PROBE_CHILD=1) at the same config, because the
# tunnel can pass a tiny op and still wedge on a model-sized compile
# (.claude/skills/verify/SKILL.md). A passing stage 2 also leaves the
# persistent compile cache warm, so the real run's compile is nearly
# free. Opt out with BENCH_NO_FALLBACK=1.
from __graft_entry__ import (_enable_compile_cache, force_cpu_fallback,
                             jax_backends_initialized, tiny_op_probe)

_PROBE_CHILD = os.environ.get("BENCH_PROBE_CHILD") == "1"


def _workload_probe() -> bool:
    import subprocess
    env = dict(os.environ)
    env["BENCH_PROBE_CHILD"] = "1"
    timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", 900))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, timeout=timeout_s)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


if (not _PROBE_CHILD and os.environ.get("BENCH_NO_FALLBACK") != "1"
        and not jax_backends_initialized()
        and not (tiny_op_probe() and _workload_probe())):
    force_cpu_fallback("bench: default platform unreachable; "
                       "falling back to CPU")

import jax
import jax.numpy as jnp

# persistent compilation cache (shared recipe, mirrors tests/conftest.py):
# after a tunnel hiccup or repeated runs, recompilation is nearly free
_enable_compile_cache()

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.train import TrainState, adam, make_train_step


def main():
    backend = "xla"
    if os.environ.get("BENCH_PALLAS") == "1":
        if jax.default_backend() != "axon" and "tpu" not in \
                jax.default_backend():
            # Mosaic lowering needs a real TPU; on the CPU fallback emit
            # the one-JSON-line contract instead of a traceback
            print(json.dumps({
                "metric": METRIC, "value": None, "unit": "ms",
                "vs_baseline": None, "backend": "pallas",
                "platform": jax.default_backend(),
                "error": "BENCH_PALLAS=1 requires a TPU backend; "
                         f"platform is {jax.default_backend()}"}))
            _DONE.set()
            sys.exit(2)
        from alphafold2_tpu.ops import (pallas_attention_enabled,
                                        use_pallas_attention)
        use_pallas_attention(True)
        if not pallas_attention_enabled():
            raise RuntimeError("BENCH_PALLAS=1 but pallas is unavailable")
        backend = "pallas"
    model = Alphafold2(dim=DIM, depth=DEPTH, heads=8, dim_head=64,
                       dtype=jnp.bfloat16)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=B, seq_len=L,
                            msa_depth=MSA, with_coords=True)
    params = model.init(jax.random.PRNGKey(1), batch["seq"],
                        msa=batch["msa"], mask=batch["mask"],
                        msa_mask=batch["msa_mask"])
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=adam(3e-4), rng=jax.random.PRNGKey(2))
    step = jax.jit(make_train_step(model), donate_argnums=(0,))

    if _PROBE_CHILD:
        # compile-only probe mode: prove the platform can compile the
        # exact bench workload (and warm the persistent cache), no timing
        step.lower(state, batch).compile()
        print("bench-probe-ok", flush=True)
        _DONE.set()
        return

    for _ in range(WARMUP):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    ms = (time.perf_counter() - t0) / ITERS * 1e3
    _DONE.set()  # measurement done; only local file IO remains

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "tools", "reference_baseline.json")
    vs_baseline = None
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            ref = json.load(f)
        cfg = ref.get("config", {})
        # only compare when the measured reference config matches this run
        if (cfg.get("dim"), cfg.get("depth"), cfg.get("seq_len"),
                cfg.get("msa_depth"), cfg.get("batch")) == \
                (DIM, DEPTH, L, MSA, B):
            vs_baseline = (ref["train_step_seconds"] * 1e3) / ms

    print(json.dumps({
        "metric": METRIC,
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": round(vs_baseline, 3) if vs_baseline else None,
        "backend": backend,
        "platform": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
