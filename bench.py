"""Benchmark: Evoformer training-step time @ 256-res crop (BASELINE.json
metric), run on whatever jax.devices() provides (the real TPU chip under the
driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N, ...}

`vs_baseline` is the speedup ratio vs the reference implementation's
matched-config training step (torch-CPU, measured on this host by
tools/measure_reference_baseline.py into tools/reference_baseline.json —
the reference publishes no numbers of its own, see BASELINE.md).

Structure: a parent orchestrator that never imports jax (a wedged TPU
tunnel hangs plugin discovery inside a blocking C call — un-interruptible
in-process) and runs each measurement attempt in a killable child
subprocess (BENCH_CHILD=1), walking a ladder of platform/config phases
under a hard deadline so that SOME labeled number always lands inside
BENCH_TIMEOUT_S:

  1. ambient platform (the TPU chip), full config    — if a tiny-op probe
     passes; the child is killed at a budget that leaves room for:
  2. CPU, full config, 1 warmup + 2 iters            — only with >=1100s left
     (~160s compile+XNN cold, ~105s/step on this 1-core host)
  3. CPU, dim128/depth2/128res, 1 warmup + 3 iters   — ~90s cold + ~10s/iter
  4. CPU, dim64/depth2/64res, 1 warmup + 3 iters     — ~63s cold + ~1s/iter
  5. if the probe failed but budget remains after a CPU number: re-probe
     and run the TPU phase late — a TPU capture overrides the fallback.

CPU phases run the measured-fastest host recipe: f32 activations (XLA:CPU
emulates bf16 in f32 — bf16 is pure convert overhead off-TPU), XNNPACK
greedy graph fusion + fast-math, and the Dense contractions routed to the
native AMX bf16 tile GEMM (native/amx_gemm.cc via ops/cpu_gemm.py) — the
same bf16-multiply/f32-accumulate precision story as the TPU MXU path.
Fallback numbers are labeled with their true config in `metric` plus
`platform`/`config_scaled`/`matmul` fields; `vs_baseline` still lands when
tools/reference_baseline.json has a matched-config torch measurement.

Each child also reports achieved TFLOP/s and, on TPU, MFU vs the chip's
bf16 peak (SURVEY.md §6). FLOPs are ANALYTIC (3x the forward contraction
count from alphafold2_tpu/utils/flops.py, custom kernels disabled during
the counting trace) — NOT XLA cost_analysis, which cannot see through
AMX FFI / pallas_call custom calls and so under-reports exactly when the
fast path is engaged; cost_analysis is still emitted as a diagnostic
field (`xla_cost_analysis_tflops`).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)

MSA, B = 5, 1

# phase ladder configs (see module docstring for the cold-timing basis)
_FULL = dict(dim=256, depth=2, seq_len=256, warmup=2, iters=10)
_CPU_FULL = dict(dim=256, depth=2, seq_len=256, warmup=1, iters=2)
_CPU_MID = dict(dim=128, depth=2, seq_len=128, warmup=1, iters=3)
_CPU_TINY = dict(dim=64, depth=2, seq_len=64, warmup=1, iters=3)

# The CPU fallback recipe (measured on this host, mid config, min of 3):
#   bf16, default flags:            18.0 s/iter   (round-3 capture's path)
#   f32, default flags:             13.6 s/iter   (XLA:CPU emulates bf16 in
#                                   f32 with rounding converts — bf16 is pure
#                                   overhead off-TPU)
#   f32 + XNN greedy + fast-math:   12.3 s/iter
#   + AMX Dense (ops/cpu_gemm.py):   9.8 s/iter   (native/amx_gemm.cc,
#                                   ~400 GFLOP/s vs ~100 for XLA:CPU's dot)
#   + AMX attention einsums:         9.0 s/iter   (batched + transposed-B)
# Full config with the complete recipe: 104.2 s/step = vs_baseline 1.545
# (torch-CPU 160.9 s). The TPU phase keeps bf16 (the MXU dtype).
_CPU_XLA_FLAGS = (
    "--xla_cpu_experimental_xnn_graph_fusion_mode=XNN_GRAPH_FUSION_MODE_GREEDY"
    " --xla_cpu_enable_fast_math=true"
    " --xla_cpu_fast_math_honor_nans=false"
    " --xla_cpu_fast_math_honor_infs=false")

# bf16 peak FLOP/s per chip, for MFU. The tunneled chip is a v5e
# (BASELINE.md); CPU gets tflops but no mfu (no meaningful peak).
_TPU_PEAK_FLOPS = 197e12


def _cfg_from_env() -> dict:
    return dict(
        dim=int(os.environ.get("BENCH_DIM", _FULL["dim"])),
        depth=int(os.environ.get("BENCH_DEPTH", _FULL["depth"])),
        seq_len=int(os.environ.get("BENCH_LEN", _FULL["seq_len"])),
        warmup=max(1, int(os.environ.get("BENCH_WARMUP", _FULL["warmup"]))),
        iters=max(1, int(os.environ.get("BENCH_ITERS", _FULL["iters"]))),
    )


def _metric_name(cfg: dict) -> str:
    return (f"evoformer_distogram_train_step@{cfg['seq_len']}res"
            f"(dim{cfg['dim']},depth{cfg['depth']},msa{MSA},b{B})")


def _lookup_baseline(cfg: dict):
    """Matched-config reference step-time (seconds) or None."""
    path = os.path.join(_REPO, "tools", "reference_baseline.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        ref = json.load(f)
    # `entries` is the canonical list; a file from the original
    # single-config schema has only top-level keys
    entries = list(ref.get("entries", []))
    if not entries and "config" in ref:
        entries = [{"config": ref["config"],
                    "train_step_seconds": ref.get("train_step_seconds")}]
    for e in entries:
        c = e.get("config", {})
        if (c.get("dim"), c.get("depth"), c.get("seq_len"),
                c.get("msa_depth"), c.get("batch")) == \
                (cfg["dim"], cfg["depth"], cfg["seq_len"], MSA, B):
            return e.get("train_step_seconds")
    return None


# --------------------------------------------------------------------------
# child: one measurement on the ambient platform
# --------------------------------------------------------------------------

def _xla_flops_of(compiled) -> float | None:
    """XLA cost_analysis flops — DIAGNOSTIC ONLY. It cannot see through
    custom calls (AMX FFI, pallas_call), so it under-reports exactly when
    the fast path is engaged (observed r03->r04: reported tflops fell 10x
    while the step got 2x faster). The number of record is the analytic
    count from alphafold2_tpu.utils.flops (round-4 VERDICT #2)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _child_main() -> int:
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _enable_compile_cache

    _enable_compile_cache()
    cfg = _cfg_from_env()
    metric = _metric_name(cfg)

    backend = "xla"
    if os.environ.get("BENCH_PALLAS") == "1":
        platform = jax.default_backend()
        if platform != "axon" and "tpu" not in platform:
            # Mosaic lowering needs a real TPU; on a CPU platform emit the
            # one-JSON-line contract instead of a traceback
            print(json.dumps({
                "metric": metric, "value": None, "unit": "ms",
                "vs_baseline": None, "backend": "pallas",
                "platform": platform,
                "error": "BENCH_PALLAS=1 requires a TPU backend; "
                         f"platform is {platform}"}), flush=True)
            return 2
        from alphafold2_tpu.ops import (pallas_attention_enabled,
                                        use_pallas_attention)
        use_pallas_attention(True)
        if not pallas_attention_enabled():
            raise RuntimeError("BENCH_PALLAS=1 but pallas is unavailable")
        backend = "pallas"

    from alphafold2_tpu import Alphafold2
    from alphafold2_tpu.data.synthetic import synthetic_batch
    from alphafold2_tpu.train import TrainState, adam, make_train_step

    # default bf16 — the production dtype on the TPU MXU. The CPU phases
    # override to float32 via _cpu_env (XLA:CPU emulates bf16 in f32 with
    # rounding converts, so bf16 is pure overhead off-TPU: 18.0 vs 13.6
    # s/iter at the mid config — see the _CPU_XLA_FLAGS comment).
    dtype = jnp.dtype(os.environ.get("BENCH_DTYPE", "bfloat16"))
    # Shallow trunks unroll without scan+remat: the remat recompute (~1
    # extra trunk forward in the backward) costs more than the activation
    # memory it saves on a 16 GB chip — measured on the v5e at the full
    # config: 92.4 ms (scan+remat) -> 75.9 ms unrolled (MFU 0.158 ->
    # 0.193). Deep trunks (the depth-48 flagship) need scan+remat to fit.
    # BENCH_SCAN=1/0 overrides.
    if os.environ.get("BENCH_SCAN") in ("0", "1"):
        use_scan = os.environ.get("BENCH_SCAN") == "1"
    else:
        use_scan = cfg["depth"] > 4
    model = Alphafold2(dim=cfg["dim"], depth=cfg["depth"], heads=8,
                       dim_head=64, dtype=dtype, use_scan=use_scan)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=B,
                            seq_len=cfg["seq_len"], msa_depth=MSA,
                            with_coords=True)
    params = model.init(jax.random.PRNGKey(1), batch["seq"],
                        msa=batch["msa"], mask=batch["mask"],
                        msa_mask=batch["msa_mask"])
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=adam(3e-4), rng=jax.random.PRNGKey(2))
    step = jax.jit(make_train_step(model), donate_argnums=(0,))
    compiled = step.lower(state, batch).compile()
    # analytic model FLOPs (3x forward contraction count, custom kernels
    # disabled for the counting trace): identical across AMX/Pallas/XLA
    # runs of one config by construction — the MFU numerator
    from alphafold2_tpu.utils.flops import train_step_flops
    flops = train_step_flops(model, params, batch)
    xla_flops = _xla_flops_of(compiled)

    # Barrier discipline: under the axon tunnel block_until_ready was
    # observed returning before device completion (r05: 3.9 ms "steps" =
    # 736 TFLOP/s on a 197-peak chip). device_get of the loss cannot
    # complete before the computation that produces it, and the steps are
    # chained through `state`, so one final fetch serializes the whole
    # timed window; its single tunnel round-trip amortizes over `iters`.
    for _ in range(cfg["warmup"]):
        state, metrics = step(state, batch)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(cfg["iters"]):
        state, metrics = step(state, batch)
    loss_val = float(jax.device_get(metrics["loss"]))
    ms = (time.perf_counter() - t0) / cfg["iters"] * 1e3

    platform = jax.default_backend()
    ref_s = _lookup_baseline(cfg)
    tflops = round(flops / (ms / 1e3) / 1e12, 3) if flops else None
    from __graft_entry__ import is_tpu_platform
    is_tpu = is_tpu_platform(platform)
    mfu = (round(flops / (ms / 1e3) / _TPU_PEAK_FLOPS, 4)
           if (flops and is_tpu) else None)

    # provenance from the compiled step itself, not the flag: the AMX
    # custom call is either in the HLO of the measured program or it isn't
    try:
        amx_engaged = "af2_amx_gemm" in compiled.as_text()
    except Exception:
        amx_engaged = False
    matmul = "amx-bf16" if amx_engaged else backend

    print(json.dumps({
        "metric": metric,
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": round(ref_s * 1e3 / ms, 3) if ref_s else None,
        "backend": backend,
        "matmul": matmul,
        "platform": platform,
        "dtype": dtype.name,
        "use_scan": use_scan,
        "warmup": cfg["warmup"],
        "iters": cfg["iters"],
        "tflops": tflops,
        "loss": round(loss_val, 4),
        "flops_model": "analytic-3x-forward (utils/flops.py)",
        "xla_cost_analysis_tflops": (
            round(xla_flops / (ms / 1e3) / 1e12, 3) if xla_flops else None),
        "mfu": mfu,
        "config_scaled": (cfg["dim"], cfg["depth"], cfg["seq_len"]) !=
                         (_FULL["dim"], _FULL["depth"], _FULL["seq_len"]),
    }), flush=True)
    return 0


# --------------------------------------------------------------------------
# parent: phase ladder under a hard deadline (never imports jax)
# --------------------------------------------------------------------------

def _watchdog(seconds: float, done: threading.Event):
    """Absolute last resort: if orchestration itself wedges, emit the JSON
    contract and die. Daemon thread, not SIGALRM — the failure mode is a
    blocking C call where Python signal handlers never run."""

    def waiter():
        if not done.wait(seconds):
            print(json.dumps({
                "metric": _metric_name(_cfg_from_env()),
                "value": None, "unit": "ms", "vs_baseline": None,
                "error": f"bench watchdog fired after {seconds:.0f}s"}),
                flush=True)
            os._exit(2)

    threading.Thread(target=waiter, daemon=True).start()


def _run_child(cfg: dict, env: dict, timeout_s: float, label: str):
    """Run one measurement child; return (parsed_json | None, note)."""
    env = dict(env)
    env["BENCH_CHILD"] = "1"
    env["BENCH_DIM"] = str(cfg["dim"])
    env["BENCH_DEPTH"] = str(cfg["depth"])
    env["BENCH_LEN"] = str(cfg["seq_len"])
    env["BENCH_WARMUP"] = str(cfg["warmup"])
    env["BENCH_ITERS"] = str(cfg["iters"])
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env, cwd=_REPO,
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        def _txt(b):
            if isinstance(b, bytes):
                b = b.decode(errors="replace")
            return (b or "")[-500:].strip()
        return None, (f"{label}: timed out after {timeout_s:.0f}s "
                      f"(stdout tail: {_txt(e.stdout)!r}, "
                      f"stderr tail: {_txt(e.stderr)!r})")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                out = json.loads(line)
            except json.JSONDecodeError:
                continue
            if out.get("value") is not None:
                return out, f"{label}: ok"
            return None, f"{label}: {out.get('error', 'null value')}"
    return None, (f"{label}: child rc={proc.returncode}, no JSON "
                  f"(stderr tail: {proc.stderr[-300:].strip()!r})")


def _cpu_env() -> dict:
    from __graft_entry__ import _scrubbed_cpu_env
    env = _scrubbed_cpu_env(1)
    env.pop("BENCH_PALLAS", None)  # pallas needs TPU; CPU phases drop it
    # CPU fallback recipe (see _CPU_XLA_FLAGS comment): f32 + XNN greedy +
    # fast-math + AMX Dense routing. BENCH_DTYPE/AF2_CPU_AMX stay
    # user-overridable.
    env.setdefault("BENCH_DTYPE", "float32")
    env.setdefault("AF2_CPU_AMX", "1")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " +
                        _CPU_XLA_FLAGS).strip()
    return env


def _parent_main() -> int:
    t_start = time.monotonic()
    total = float(os.environ.get("BENCH_TIMEOUT_S", 1500))
    done = threading.Event()
    _watchdog(total - 5, done)
    deadline = t_start + total - 30

    def remaining() -> float:
        return deadline - time.monotonic()

    from __graft_entry__ import tiny_op_probe

    notes = []
    result = None
    pallas = os.environ.get("BENCH_PALLAS") == "1"
    no_fallback = os.environ.get("BENCH_NO_FALLBACK") == "1"

    # phase 1: ambient platform (TPU), full config. Budget: reserve a
    # full cpu-full slot (~1150s incl. its own tail) when possible, so a
    # HALF-wedged tunnel (tiny op passes, model compile hangs — observed
    # mode) that eats the whole TPU budget still leaves the cpu-full rung
    # viable; the cpu-mid rung alone would capture a number that LOSES to
    # torch (ours ~9.0 s vs torch 7.88 s at dim128 — small shapes favor
    # eager oneDNN; the headline config wins 1.67x). A healthy chip only
    # needs ~240s (20-40s compile + 12 steps at ~0.1s).
    if os.environ.get("BENCH_NO_TPU") != "1":
        if tiny_op_probe(timeout_s=min(60, max(10, remaining() - 120))):
            if no_fallback:
                budget = min(900.0, remaining() - 30)
            else:
                # floor at the healthy-chip need (240s covers compile +
                # 12 steps with margin) and NEVER grant more than leaves
                # the cpu-full reserve — a larger grant on a small window
                # would hand the whole window to a wedged compile
                budget = min(900.0, max(240.0, remaining() - 1150))
            if budget > 120:
                cfg = _cfg_from_env()
                result, note = _run_child(cfg, dict(os.environ), budget,
                                          "tpu-full")
                notes.append(note)
            else:
                notes.append(f"tpu-full skipped: only {budget:.0f}s budget "
                             "after CPU-ladder reserve")
        else:
            notes.append("tiny-op probe failed (tunnel wedged?)")

    if result is None and pallas:
        # no CPU story for pallas: emit the contract error and stop
        print(json.dumps({
            "metric": _metric_name(_cfg_from_env()), "value": None,
            "unit": "ms", "vs_baseline": None, "backend": "pallas",
            "error": "; ".join(notes) or "TPU unreachable"}), flush=True)
        done.set()
        return 2

    # phases 2-4: CPU ladder, largest config the budget allows
    if result is None and not no_fallback:
        print("bench: default platform unreachable or too slow; "
              "falling back to CPU", file=sys.stderr, flush=True)
        cpu_env = _cpu_env()
        # cpu-full worst case ~440s uncontended (f32+AMX recipe: ~20s
        # warm-cache / ~120s cold compile + 3 steps at ~105s); the 900s cap
        # leaves contention headroom while the deadline math still closes:
        # probe 60 + 900 + mid 300 + tiny 80 < total - 30
        ladder = [
            (_CPU_FULL, 900.0, 1100.0, "cpu-full"),
            (_CPU_MID, 300.0, 220.0, "cpu-mid"),
            (_CPU_TINY, 0.0, 75.0, "cpu-tiny"),
        ]
        for cfg, budget_cap, min_needed, label in ladder:
            if result is not None or remaining() < min_needed:
                continue
            budget = remaining() - (90 if label != "cpu-tiny" else 5)
            if budget_cap:
                budget = min(budget, budget_cap)
            result, note = _run_child(cfg, cpu_env, budget, label)
            notes.append(note)

    # late TPU retry: if the tunnel was wedged at phase 1 but the CPU
    # ladder left budget, probe again — a TPU number (with MFU) beats any
    # CPU fallback number, so it overrides
    if (os.environ.get("BENCH_NO_TPU") != "1" and not no_fallback
            and notes and notes[0].startswith("tiny-op probe failed")
            and remaining() > 420):
        if tiny_op_probe(timeout_s=30):
            tpu_result, note = _run_child(_cfg_from_env(), dict(os.environ),
                                          remaining() - 60, "tpu-full-retry")
            notes.append(note)
            if tpu_result is not None:
                result = tpu_result
        else:
            notes.append("late tpu re-probe: still wedged")

    if result is not None:
        result["phases"] = notes
        print(json.dumps(result), flush=True)
        done.set()
        return 0

    print(json.dumps({
        "metric": _metric_name(_cfg_from_env()), "value": None,
        "unit": "ms", "vs_baseline": None,
        "error": "; ".join(notes) or "no phase produced a number"}),
        flush=True)
    done.set()
    return 2


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        sys.exit(_child_main())
    sys.exit(_parent_main())
