"""Benchmark: Evoformer training-step time @ 256-res crop (BASELINE.json
metric), run on whatever jax.devices() provides (the real TPU chip under the
driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}

`vs_baseline` is the speedup ratio vs the reference implementation's
matched-config training step (torch, measured on this host by
tools/measure_reference_baseline.py into tools/reference_baseline.json —
the reference publishes no numbers of its own, see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_DONE = threading.Event()

DIM = int(os.environ.get("BENCH_DIM", 256))
DEPTH = int(os.environ.get("BENCH_DEPTH", 2))
L = int(os.environ.get("BENCH_LEN", 256))
MSA, B = 5, 1
WARMUP = max(1, int(os.environ.get("BENCH_WARMUP", 2)))
ITERS = max(1, int(os.environ.get("BENCH_ITERS", 10)))

METRIC = (f"evoformer_distogram_train_step@{L}res(dim{DIM},"
          f"depth{DEPTH},msa{MSA},b{B})")


def _watchdog(seconds: int):
    """If the TPU tunnel is wedged, fail loudly with a JSON line instead
    of hanging the driver. A daemon thread (not SIGALRM): the hang sits
    inside a blocking C call during jax plugin discovery, so Python-level
    signal handlers would never run."""

    def waiter():
        if not _DONE.wait(seconds):
            print(json.dumps({
                "metric": METRIC,
                "value": None, "unit": "ms", "vs_baseline": None,
                "error": f"bench timed out after {seconds}s "
                         "(device backend unreachable?)"}), flush=True)
            os._exit(2)

    threading.Thread(target=waiter, daemon=True).start()


_watchdog(int(os.environ.get("BENCH_TIMEOUT_S", 1500)))

import jax
import jax.numpy as jnp

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.train import TrainState, adam, make_train_step


def main():
    backend = "xla"
    if os.environ.get("BENCH_PALLAS") == "1":
        from alphafold2_tpu.ops import (pallas_attention_enabled,
                                        use_pallas_attention)
        use_pallas_attention(True)
        if not pallas_attention_enabled():
            raise RuntimeError("BENCH_PALLAS=1 but pallas is unavailable")
        backend = "pallas"
    model = Alphafold2(dim=DIM, depth=DEPTH, heads=8, dim_head=64,
                       dtype=jnp.bfloat16)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=B, seq_len=L,
                            msa_depth=MSA, with_coords=True)
    params = model.init(jax.random.PRNGKey(1), batch["seq"],
                        msa=batch["msa"], mask=batch["mask"],
                        msa_mask=batch["msa_mask"])
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=adam(3e-4), rng=jax.random.PRNGKey(2))
    step = jax.jit(make_train_step(model), donate_argnums=(0,))

    for _ in range(WARMUP):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    ms = (time.perf_counter() - t0) / ITERS * 1e3
    _DONE.set()  # measurement done; only local file IO remains

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "tools", "reference_baseline.json")
    vs_baseline = None
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            ref = json.load(f)
        cfg = ref.get("config", {})
        # only compare when the measured reference config matches this run
        if (cfg.get("dim"), cfg.get("depth"), cfg.get("seq_len"),
                cfg.get("msa_depth"), cfg.get("batch")) == \
                (DIM, DEPTH, L, MSA, B):
            vs_baseline = (ref["train_step_seconds"] * 1e3) / ms

    print(json.dumps({
        "metric": METRIC,
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": round(vs_baseline, 3) if vs_baseline else None,
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
